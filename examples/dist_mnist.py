"""Distributed data-parallel MNIST training — the framework's minimal real
training workload.

Parity: examples/v1alpha2/dist-mnist/dist_mnist.py in the reference
(between-graph replication + replica_device_setter + SyncReplicasOptimizer),
rebuilt TPU-first: the operator-injected env initializes jax.distributed,
the global batch is sharded over a dp mesh spanning every device of every
process, and XLA's all-reduce replaces both the PS round-trip and
SyncReplicasOptimizer. Uses synthetic MNIST-shaped data so it runs hermetic
(no dataset download; the reference pulls MNIST over the network).

Run standalone (single process) or as a TPUJob container command.
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch", type=int, default=256,
                   help="per-process batch size (global = this x processes)")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--target-loss", type=float, default=0.25,
                   help="exit non-zero unless final loss is below this")
    args = p.parse_args(argv)

    from tf_operator_tpu.train import distributed

    topo = distributed.initialize()

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.mnist import MnistCNN
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import replicate, shard_batch
    from tf_operator_tpu.train.data import synthetic_mnist
    from tf_operator_tpu.train.steps import (
        TrainState,
        make_classifier_train_step,
        sgd_momentum,
    )

    devices = jax.devices()
    print(
        f"dist_mnist: process {topo.process_id}/{topo.num_processes}, "
        f"{len(devices)} global devices",
        flush=True,
    )
    mesh = create_mesh({"dp": len(devices)}, devices)

    model = MnistCNN()
    x0 = jnp.zeros((8, 28, 28, 1), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x0, train=True)
    tx = sgd_momentum(args.lr)
    state = TrainState.create(variables["params"], tx)
    state = replicate(mesh, state)
    step = make_classifier_train_step(model, tx, mesh, has_batch_stats=False)

    data = synthetic_mnist(args.batch, seed=topo.process_id)
    t0 = time.perf_counter()
    loss = float("inf")
    for i in range(args.steps):
        batch = shard_batch(mesh, next(data))
        state, metrics = step(state, batch)
        if (i + 1) % 20 == 0 or i == 0:
            loss = float(metrics["loss"])
            acc = float(metrics["accuracy"])
            print(f"dist_mnist: step {i+1} loss={loss:.4f} acc={acc:.3f}", flush=True)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    global_batch = args.batch * topo.num_processes
    print(
        f"dist_mnist: {args.steps} steps in {dt:.1f}s "
        f"({args.steps * global_batch / dt:.0f} img/s global batch "
        f"{global_batch}), final loss {loss:.4f}",
        flush=True,
    )
    if loss > args.target_loss:
        print(f"dist_mnist: FAILED (loss {loss:.4f} > {args.target_loss})", flush=True)
        return 1
    print("dist_mnist: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
