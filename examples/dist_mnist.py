"""Distributed data-parallel MNIST training — the framework's minimal real
training workload.

Parity: examples/v1alpha2/dist-mnist/dist_mnist.py in the reference
(between-graph replication + replica_device_setter + SyncReplicasOptimizer),
rebuilt TPU-first: the operator-injected env initializes jax.distributed,
the global batch is sharded over a dp mesh spanning every device of every
process, and XLA's all-reduce replaces both the PS round-trip and
SyncReplicasOptimizer. Uses synthetic MNIST-shaped data so it runs hermetic
(no dataset download; the reference pulls MNIST over the network).

Run standalone (single process) or as a TPUJob container command.
"""

from __future__ import annotations

import argparse
import sys
import time


def run_evaluator(args) -> int:
    """Follow the trainer's checkpoints: evaluate every new step on
    held-out data, exit 0 once the final step is evaluated."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.mnist import MnistCNN
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.train.checkpoint import CheckpointManager
    from tf_operator_tpu.train.data import synthetic_mnist
    from tf_operator_tpu.train.steps import (
        TrainState,
        evaluate,
        make_classifier_eval_step,
        sgd_momentum,
    )

    if not args.checkpoint_dir:
        print("dist_mnist eval: --checkpoint-dir is required", flush=True)
        return 2
    devices = jax.devices()
    mesh = create_mesh({"dp": len(devices)}, devices)
    model = MnistCNN()
    x0 = jnp.zeros((8, 28, 28, 1), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x0, train=True)
    template = TrainState.create(variables["params"], sgd_momentum(args.lr))
    eval_step = make_classifier_eval_step(model, mesh, has_batch_stats=False)
    heldout_stream = synthetic_mnist(args.batch, seed=10_000)
    heldout = [next(heldout_stream) for _ in range(4)]

    ckpt = CheckpointManager(args.checkpoint_dir, max_to_keep=2)
    last = -1
    deadline = time.monotonic() + args.eval_timeout
    while True:
        try:
            ckpt.reload()  # see the TRAINER's writes (orbax caches steps)
            latest = ckpt.latest_step()
        except Exception:
            latest = None
        step_done = -1 if latest is None else int(latest)
        restored = None
        if step_done > last:
            try:
                # Restore ONLY when a new step exists — a full restore per
                # 300ms poll would be continuous redundant disk IO.
                restored = ckpt.restore(step_done, template)
            except Exception:
                # Racing the trainer's save/GC: retry, but FALL THROUGH to
                # the deadline check — a persistently corrupt checkpoint
                # must end in exit 1, not an infinite poll loop.
                restored = None
        if restored is not None:
            m = evaluate(eval_step, restored, iter(heldout))
            print(
                f"dist_mnist eval: step {step_done} "
                f"accuracy={m['accuracy']:.3f} loss={m['loss']:.4f}",
                flush=True,
            )
            last = step_done
            deadline = time.monotonic() + args.eval_timeout
            if step_done >= args.steps - 1:
                print("dist_mnist eval: DONE", flush=True)
                return 0
        if time.monotonic() > deadline:
            print(
                f"dist_mnist eval: no new checkpoint in {args.eval_timeout}s",
                flush=True,
            )
            return 1
        time.sleep(0.3)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch", type=int, default=256,
                   help="per-process batch size (global = this x processes)")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--target-loss", type=float, default=0.25,
                   help="exit non-zero unless final loss is below this")
    p.add_argument("--checkpoint-dir", default=None,
                   help="save/resume train state here (orbax)")
    p.add_argument("--checkpoint-interval", type=int, default=1,
                   help="save every N steps")
    p.add_argument("--fail-at-step", type=int, default=None,
                   help="simulate preemption: first incarnation exits 138 "
                        "(user-retryable) at this step after checkpointing")
    p.add_argument("--eval-timeout", type=float, default=120.0,
                   help="evaluator role: exit 1 after this long without a "
                        "new checkpoint")
    args = p.parse_args(argv)
    if args.fail_at_step is not None and not args.checkpoint_dir:
        # Without a checkpoint every incarnation restarts at step 0, hits
        # the failure step again, and the retryable exit crash-loops the job.
        p.error("--fail-at-step requires --checkpoint-dir")

    from tf_operator_tpu.train import distributed

    topo = distributed.initialize()
    if topo.role == "evaluator":
        # Evaluator replica: excluded from the training rendezvous by the
        # operator (cluster_spec evaluator exclusion); follows the
        # trainer's checkpoints and evaluates each one on held-out data —
        # the reference's chief/evaluator split, workload-side.
        return run_evaluator(args)

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.mnist import MnistCNN
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import replicate, shard_batch
    from tf_operator_tpu.train.data import synthetic_mnist
    from tf_operator_tpu.train.steps import (
        TrainState,
        make_classifier_train_step,
        sgd_momentum,
    )

    devices = jax.devices()
    print(
        f"dist_mnist: process {topo.process_id}/{topo.num_processes}, "
        f"{len(devices)} global devices",
        flush=True,
    )
    mesh = create_mesh({"dp": len(devices)}, devices)

    model = MnistCNN()
    x0 = jnp.zeros((8, 28, 28, 1), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x0, train=True)
    tx = sgd_momentum(args.lr)
    state = TrainState.create(variables["params"], tx)
    state = replicate(mesh, state)
    step = make_classifier_train_step(model, tx, mesh, has_batch_stats=False)

    ckpt = None
    start_step = 0
    resumed = False
    if args.checkpoint_dir:
        from tf_operator_tpu.train.checkpoint import (
            CheckpointManager,
            resume_min_step,
        )

        ckpt = CheckpointManager(
            args.checkpoint_dir, max_to_keep=2,
            save_interval_steps=args.checkpoint_interval,
        )
        # min_step: never resume below the operator's acked step — the
        # CheckpointManager follower caveat (reload-before-latest) applied
        # at the resume call site.
        state, start_step = ckpt.restore_or_init(
            state, min_step=resume_min_step()
        )
        # resumed (not the clamped start_step) gates the preemption sim:
        # with --steps 1 the clamp forces start_step back to 0, and a
        # start_step==0 guard would re-fire exit 138 forever.
        resumed = start_step > 0
        # Re-run at least the final step so the loss acceptance check below
        # always executes — a fully-resumed run must not skip straight to
        # success (the previous incarnation may have failed the target).
        start_step = max(0, min(start_step, args.steps - 1))
        if resumed:
            print(f"dist_mnist: resumed from step {start_step}", flush=True)

    data = synthetic_mnist(args.batch, seed=topo.process_id)
    # Resume must continue the batch stream at the step offset, not replay
    # batches 0..N — the pattern a real data pipeline needs (a replayed
    # stream would double-train early batches after every preemption).
    for _ in range(start_step):
        next(data)
    t0 = time.perf_counter()
    loss = float("inf")
    metrics = None
    for i in range(start_step, args.steps):
        batch = shard_batch(mesh, next(data))
        state, metrics = step(state, batch)
        if ckpt is not None:
            # Force the FINAL step past save_interval_steps: a follower
            # evaluator's completion condition is a checkpoint at steps-1.
            ckpt.save(i, state, force=(i == args.steps - 1))
        if (
            args.fail_at_step is not None
            and i == args.fail_at_step
            and not resumed
        ):
            # Simulated preemption: checkpoint is durable, then die with
            # the user-retryable exit code (SIGUSR1 convention, 138) so the
            # ExitCode restart policy relaunches this replica.
            if ckpt is not None:
                ckpt.wait()
            print(f"dist_mnist: simulating preemption at step {i}", flush=True)
            import os as _os

            _os._exit(138)
        if (i + 1) % 20 == 0 or i == start_step:
            loss = float(metrics["loss"])
            acc = float(metrics["accuracy"])
            print(f"dist_mnist: step {i+1} loss={loss:.4f} acc={acc:.3f}", flush=True)
    if ckpt is not None:
        ckpt.close()
    if metrics is None:  # steps <= start_step: no step ran this incarnation
        print("dist_mnist: no steps to run", flush=True)
        return 0
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    steps_run = args.steps - start_step
    global_batch = args.batch * topo.num_processes
    print(
        f"dist_mnist: {steps_run} steps in {dt:.1f}s "
        f"({steps_run * global_batch / dt:.0f} img/s global batch "
        f"{global_batch}), final loss {loss:.4f}",
        flush=True,
    )
    if loss > args.target_loss:
        print(f"dist_mnist: FAILED (loss {loss:.4f} > {args.target_loss})", flush=True)
        return 1
    print("dist_mnist: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
