"""Multislice training example: two jax.distributed process groups bridged
over the DCN channel.

Run as a TPUJob with ``tpu: {acceleratorType: ..., numSlices: N}``. Each
slice bootstraps its OWN jax.distributed group from the operator-injected
in-slice contract (TPU_COORDINATOR_ADDRESS / TPU_WORKER_ID /
TPU_NUM_PROCESSES — one coordinator per slice), trains data-parallel inside
the slice, and synchronizes parameters across slices each step through the
MEGASCALE-shaped DCN contract (train/dcn.py cross_slice_mean). This is the
process-group-level proof SURVEY.md §2.9 asks for: the MEGASCALE env is not
just strings — it bootstraps two coordinators plus a cross-group reduction.

The model is a linear regression on synthetic data whose optimum DIFFERS
per slice; only the cross-slice average converges to the global optimum, so
convergence itself proves the DCN leg carries real data.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--batch", type=int, default=64, help="per-slice batch")
    p.add_argument("--dim", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.3)
    # The per-step average's fixed point sits NEAR the global optimum with a
    # sampled-covariance offset (finite batches); the slice-LOCAL optima sit
    # ~1.4 away, so 0.5 still cleanly discriminates "DCN moved data" from
    # "slices trained alone".
    p.add_argument("--tol", type=float, default=0.5)
    p.add_argument("--fsdp", action="store_true",
                   help="shard params + momentum over the IN-SLICE axis "
                        "(ZeRO within each slice, dp across DCN) instead "
                        "of replicating — the dcn x fsdp deployment shape")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from tf_operator_tpu.train import dcn, distributed

    topo = distributed.initialize()  # in-slice jax.distributed group
    import os

    slice_id = int(os.environ.get("MEGASCALE_SLICE_ID", "0"))
    num_slices = int(os.environ.get("MEGASCALE_NUM_SLICES", "1"))
    channel = dcn.channel_from_env(in_slice_process_id=topo.process_id)

    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    replicated = NamedSharding(mesh, P())
    # --fsdp: params + momentum live sharded over the slice's devices
    # (dim 0 over the in-slice axis); only the per-step cross-slice sync
    # gathers them. The same axis shards the batch rows, so in-slice
    # collectives (param all-gather, grad reduce-scatter — XLA inserts
    # them under the shardings) ride ICI while DCN carries one param-set
    # per step, exactly the dcn x fsdp shape of dryrun_multichip path 6b.
    w_sharding = NamedSharding(mesh, P("dp")) if args.fsdp else replicated
    if args.fsdp and args.dim % len(devices):
        raise SystemExit(f"--fsdp: --dim {args.dim} must divide by "
                         f"{len(devices)} in-slice devices")
    gather = jax.jit(lambda a: a, out_shardings=replicated)

    # Ground truth differs per slice: w*_slice = base + slice_id. The
    # cross-slice mean of the optima is base + (num_slices-1)/2; only a
    # job whose DCN sync works converges there.
    rng = np.random.default_rng(42)
    w_base = rng.normal(size=(args.dim,)).astype(np.float32)
    w_true_local = w_base + np.float32(slice_id)
    w_true_global = w_base + np.float32((num_slices - 1) / 2)

    mu = 0.5 if args.fsdp else 0.0  # momentum: gives --fsdp an optimizer
    # moment to shard; the fixed point is unchanged.

    @jax.jit
    def step(w, v, x, y):
        def loss_fn(w):
            pred = x @ w
            return jnp.mean((pred - y) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(w)
        # In-slice dp: batch rows sharded over the slice's processes; the
        # gradient mean is a psum XLA inserts under the sharding.
        v = mu * v + g
        return w - args.lr * v, v, loss

    w = jax.device_put(jnp.zeros((args.dim,), jnp.float32), w_sharding)
    v = jax.device_put(jnp.zeros((args.dim,), jnp.float32), w_sharding)
    data_rng = np.random.default_rng(1000 + slice_id)
    loss0 = None
    for i in range(args.steps):
        x = data_rng.normal(size=(args.batch, args.dim)).astype(np.float32)
        y = x @ w_true_local
        xg = jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx]
        )
        yg = jax.make_array_from_callback(
            y.shape, sharding, lambda idx: y[idx]
        )
        w, v, loss = step(w, v, xg, yg)
        if loss0 is None:
            loss0 = float(loss)
        # Cross-slice sync each step (sync data-parallel over DCN): the
        # sharded state is gathered for the host-side DCN hop and
        # re-sharded on return — momentum too, so every slice runs the
        # identical optimizer trajectory. One pytree exchange: DCN
        # latency dominates the sync, so {w, v} share a round trip.
        if args.fsdp:
            synced = dcn.cross_slice_mean(
                channel,
                {"w": np.asarray(gather(w)), "v": np.asarray(gather(v))},
            )
            w = jax.device_put(jnp.asarray(synced["w"]), w_sharding)
            v = jax.device_put(jnp.asarray(synced["v"]), w_sharding)
        else:
            w = jax.device_put(
                jnp.asarray(dcn.cross_slice_mean(channel, np.asarray(w))),
                w_sharding,
            )

    if args.fsdp:
        # The shape claim itself: params and the momentum moment are
        # genuinely sharded over the in-slice axis.
        for name, arr in (("w", w), ("v", v)):
            spec = str(getattr(arr.sharding, "spec", ""))
            if "dp" not in spec:
                print(f"dist_multislice: {name} not in-slice sharded "
                      f"({spec!r})")
                return 1
        print(f"dist_multislice: fsdp state sharded over "
              f"{len(devices)} in-slice devices", flush=True)

    w_full = np.asarray(gather(w))
    err = float(np.linalg.norm(w_full - w_true_global))
    local_err = float(np.linalg.norm(w_full - w_true_local))
    print(
        f"dist_multislice: slice {slice_id}/{num_slices} proc "
        f"{topo.process_id}/{topo.num_processes} loss0={loss0:.3f} "
        f"global_err={err:.4f} local_err={local_err:.4f}",
        flush=True,
    )

    # Cross-slice agreement: every slice must hold the identical params.
    if channel is not None:
        mean_w = dcn.cross_slice_mean(channel, w_full)
        agreement = float(np.linalg.norm(mean_w - w_full))
        if agreement > 1e-5:
            print(f"dist_multislice: DIVERGED across slices ({agreement})")
            return 1
        channel.close()

    if num_slices > 1:
        # Converged to the GLOBAL optimum, not the slice-local one — the
        # DCN reduction demonstrably moved information between the groups.
        if err > args.tol:
            print(f"dist_multislice: global err {err} > {args.tol}")
            return 1
        if local_err < err:
            print("dist_multislice: converged to LOCAL optimum (no DCN?)")
            return 1
    elif err > args.tol and local_err > args.tol:
        return 1
    print("dist_multislice: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
