"""Smoke-test workload: prove the operator-injected topology contract works.

Parity: examples/tf_sample/tf_smoke.py in the reference — parse the injected
cluster config, bring up the runtime's distributed fabric, run a collective
over every task, print the result. TPU-first: the cluster contract is the
TPU_* env the operator injects (controller/cluster_spec.py), the fabric is
``jax.distributed`` + an SPMD psum over the global device mesh rather than a
tf.train.Server gRPC graph.

Run as the container command of a TPUJob; exits 0 when the collective
matches the expected global device count, non-zero otherwise. Works on a
single process (no distributed env) too.
"""

from __future__ import annotations

import sys


def main() -> int:
    from tf_operator_tpu.train import distributed

    topo = distributed.initialize()
    print(
        f"tpu_smoke: process {topo.process_id}/{topo.num_processes} "
        f"coordinator={topo.coordinator_address} "
        f"accelerator={topo.accelerator_type} hosts={topo.worker_hostnames}",
        flush=True,
    )

    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    n = len(devices)
    print(f"tpu_smoke: global devices = {n} ({devices[0].platform})", flush=True)

    # The tf_smoke matmul-on-every-task analog: every process contributes its
    # local shard; the global sum must see all of them.
    mesh = Mesh(devices, ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    local = np.ones((len(jax.local_devices()), 4), np.float32)
    ones = jax.make_array_from_process_local_data(sharding, local)

    @jax.jit
    def global_sum(x):
        return x.sum()

    total = float(global_sum(ones))
    expected = float(n * 4)
    print(f"tpu_smoke: global_sum={total} expected={expected}", flush=True)
    if total != expected:
        print("tpu_smoke: FAILED", flush=True)
        return 1
    print("tpu_smoke: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
