"""Distributed long-context LM training — the framework's transformer
workload as an operator-launched job.

The companion to dist_mnist.py for the model-parallel side of the stack
(the reference has no sharded-execution sample at all — SURVEY.md §2.9;
its closest analog is the between-graph dist_mnist). One jitted train step
over a dp x sp x tp mesh spanning every process:

- sp > 1 turns on ring attention (parallel/ring_attention.py) — the
  sequence is sharded across processes and ppermute streams KV blocks
  around the ring, so context length scales with the mesh, not the chip.
- tp > 1 shards attention heads / MLP hidden / vocab (Megatron pairing,
  models/transformer.py param_sharding_rules).
- --moe-every-n swaps every Nth block's MLP for a routed expert MLP
  (Switch / GShard top-2, models/moe.py) with the load-balancing aux
  loss in the train step; --ep > 1 shards the experts over an
  expert-parallel mesh axis (the dispatch/combine einsums become
  GSPMD all-to-alls).
- --pp > 1 pipelines the block stack as GPipe stages (train/pp_lm.py)
  over a pp x dp mesh — microbatches hop stages via ppermute; composes
  with checkpoint/resume (the pipelined param tree checkpoints whole).
- The loss is the chunked cross-entropy (train/steps.py): logits never
  materialize at [B,S,V]; under sp/tp it is the vocab-parallel
  sharded_lm_xent.
- Checkpoint/resume + simulated preemption mirror dist_mnist.py so the
  ExitCode restart policy can be exercised on the LM path too.
- Checkpoint coordination (tf_operator_tpu/ckpt/): with checkpointing on,
  the operator's eviction signal (relayed by the local executor as a
  graceful SIGTERM, utils/signals.py) triggers a forced save + durable
  ack instead of being ignored, the periodic saves report progress via
  the ack file, and resume honors the injected TPU_RESUME_STEP /
  TPU_CKPT_DIR contract — so a preempted/migrated replica restarts from
  its last acked step, not the latest periodic save it happens to see.

Data is a synthetic next-token task (tokens advance by +1 mod vocab) the
model must actually learn — the acceptance check fails the replica when
final loss misses the target.
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch", type=int, default=8, help="GLOBAL batch size")
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--kv-heads", type=int, default=None,
                   help="grouped-query attention: K/V heads (must divide "
                        "the 4 query heads); the decode KV cache shrinks "
                        "by the group factor")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel axis size (ring attention)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel axis size")
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--target-loss", type=float, default=1.0)
    p.add_argument("--xent-chunk", type=int, default=None,
                   help="chunked cross-entropy chunk (default: per-device "
                        "seq / 2)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize blocks (long-context memory)")
    p.add_argument("--ring-impl", default="auto",
                   choices=("auto", "stream", "flash", "ulysses"),
                   help="sequence-parallel attention: stream (autodiff "
                        "ring, supports kv chunking), flash (custom-VJP "
                        "second-ring backward, Pallas blocks on TPU), or "
                        "ulysses (all-to-all head/sequence exchange — "
                        "needs heads/tp divisible by sp)")
    p.add_argument("--moe-every-n", type=int, default=None,
                   help="swap every Nth block's MLP for a routed expert "
                        "MLP (models/moe.py); enables the MoE path")
    p.add_argument("--moe-experts", type=int, default=8)
    p.add_argument("--moe-top-k", type=int, default=2,
                   help="1 = Switch, 2 = GShard top-2")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel mesh axis (experts sharded over "
                        "it; requires --moe-every-n)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel stages (train/pp_lm.py: the "
                        "block stack as GPipe stages; requires sp=tp=ep=1 "
                        "and layers divisible by pp)")
    p.add_argument("--pp-microbatches", type=int, default=2,
                   help="microbatches per step on the --pp path")
    p.add_argument("--pp-schedule", choices=("gpipe", "1f1b"),
                   default="gpipe",
                   help="gpipe: autodiff through the pipeline (stash "
                        "grows with microbatches); 1f1b: interleaved "
                        "fwd/bwd with an O(pp) stash — raise "
                        "--pp-microbatches to shrink the bubble without "
                        "raising memory")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="microbatches per optimizer step (gradients "
                        "averaged inside one jitted step; the global "
                        "batch must divide by this AND the microbatch "
                        "must still tile the dp axis)")
    p.add_argument("--data", default=None,
                   help="token-record file (write_token_records layout): "
                        "each process streams its disjoint shard of every "
                        "epoch through the native pipeline "
                        "(shard_id=process_id). Requires sp=tp=1 (pure "
                        "data parallelism); default: synthetic +1 chains")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-interval", type=int, default=1)
    p.add_argument("--fail-at-step", type=int, default=None,
                   help="simulate preemption: exit 138 once at this step")
    args = p.parse_args(argv)
    if args.fail_at_step is not None and not args.checkpoint_dir:
        p.error("--fail-at-step requires --checkpoint-dir")
    if args.ring_impl != "auto" and args.sp <= 1:
        # Ring attention only engages when the sequence is sharded; a
        # forced impl with sp=1 would silently train on plain attention.
        p.error("--ring-impl requires --sp > 1 (ring attention is off)")

    import os

    # Operator-injected checkpoint contract (ckpt/protocol.py): a
    # replacement pod of a checkpointing job learns its directory even
    # when the manifest never spelled one out.
    ckpt_dir = args.checkpoint_dir or os.environ.get("TPU_CKPT_DIR")
    stop_event = None
    if ckpt_dir:
        # Install BEFORE any heavy initialization: the eviction signal can
        # arrive at any point, and an uninstalled handler would kill the
        # process instead of requesting a checkpoint. Only checkpointing
        # runs trap SIGTERM — a non-checkpointing replica keeps the
        # default die-on-TERM so plain deletions stay prompt.
        from tf_operator_tpu.utils import signals

        stop_event = signals.setup_signal_handler()

    from tf_operator_tpu.train import distributed

    topo = distributed.initialize()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules
    from tf_operator_tpu.train.steps import TrainState, adamw, make_lm_train_step

    devices = jax.devices()
    n = len(devices)
    if args.ep > 1 and not args.moe_every_n:
        raise SystemExit("--ep requires --moe-every-n")
    if args.moe_every_n and args.moe_experts % args.ep:
        raise SystemExit("--moe-experts must be a multiple of --ep")
    if args.pp > 1:
        if args.sp > 1 or args.tp > 1 or args.ep > 1 or args.moe_every_n:
            raise SystemExit("--pp composes with dp only (sp/tp/ep/moe "
                             "must be off)")
        if args.layers % args.pp:
            raise SystemExit("--layers must be divisible by --pp")
        if args.data or args.grad_accum != 1:
            raise SystemExit("--pp path: no --data, --grad-accum must be 1")
        if args.batch % args.pp_microbatches:
            raise SystemExit("--batch must divide by --pp-microbatches")
    if n % (args.sp * args.tp * args.ep * args.pp):
        raise SystemExit(f"{n} devices not divisible by sp*tp*ep*pp="
                         f"{args.sp * args.tp * args.ep * args.pp}")
    if args.pp > 1:
        micro = args.batch // args.pp_microbatches
        pp_dp = n // args.pp
        if micro % pp_dp:
            raise SystemExit(
                f"microbatch size {micro} (batch/pp-microbatches) must "
                f"divide by the dp axis ({pp_dp}) — raise --batch or "
                "lower --pp-microbatches"
            )
    axes = {"dp": n // (args.sp * args.tp * args.ep * args.pp),
            "sp": args.sp, "tp": args.tp}
    if args.ep > 1:
        axes["ep"] = args.ep
    if args.pp > 1:
        axes["pp"] = args.pp
    print(
        f"dist_lm: process {topo.process_id}/{topo.num_processes}, "
        f"mesh {axes}", flush=True,
    )
    mesh = create_mesh(axes, devices)
    if args.batch % max(axes["dp"], 1) or args.seq % max(axes["sp"], 1):
        raise SystemExit(
            "batch must be a multiple of dp and seq a multiple of sp"
        )
    if args.grad_accum < 1 or args.batch % args.grad_accum or (
        (args.batch // args.grad_accum) % max(axes["dp"], 1)
    ):
        raise SystemExit(
            "--grad-accum must divide the batch, with each microbatch "
            "still a multiple of dp"
        )
    local_seq = args.seq // axes["sp"]
    if args.xent_chunk is not None:
        if args.xent_chunk <= 0 or local_seq % args.xent_chunk:
            raise SystemExit(
                f"--xent-chunk must divide the per-device seq {local_seq}"
            )
        chunk = args.xent_chunk
    else:
        chunk = local_seq // 2 if local_seq % 2 == 0 else local_seq

    moe_kw = {}
    if args.moe_every_n:
        moe_kw = dict(
            moe_every_n=args.moe_every_n, moe_experts=args.moe_experts,
            moe_top_k=args.moe_top_k,
        )
    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=4,
        n_kv_heads=args.kv_heads,
        n_layers=args.layers, d_ff=args.d_model * 2,
        max_seq_len=args.seq, dtype=jnp.float32,
        # The pp path's pipeline shard_maps itself; mesh-aware blocks are
        # for the dp/sp/tp/ep path.
        mesh=None if args.pp > 1 else mesh,
        remat=args.remat, ring_impl=args.ring_impl, **moe_kw,
    )
    model = Transformer(cfg)
    tokens0 = jnp.zeros((args.batch, args.seq), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens0)["params"]
    tx = adamw(args.lr)
    if args.pp > 1:
        from tf_operator_tpu.train.pp_lm import (
            make_pp_lm_train_step,
            pp_param_shardings,
            split_pp_params,
        )

        from tf_operator_tpu.train.pp_lm import place_pp_state

        outer, stages = split_pp_params(params, args.layers, args.pp)
        pp_tree = {"outer": outer, "stages": stages}
        pp_tree = jax.device_put(pp_tree, pp_param_shardings(mesh, pp_tree))
        state = place_pp_state(mesh, TrainState.create(pp_tree, tx))
        step = make_pp_lm_train_step(
            cfg, mesh, tx, num_micro=args.pp_microbatches,
            xent_chunk=chunk, schedule=args.pp_schedule,
        )
    else:
        rules = dict(param_sharding_rules())
        if args.ep > 1:  # expert weights split on the expert dim over "ep"
            from tf_operator_tpu.models.moe import moe_param_sharding_rules

            rules.update(moe_param_sharding_rules())
        params = shard_params_by_rules(mesh, params, rules)
        state = TrainState.create(params, tx)
        step = make_lm_train_step(
            model, tx, mesh, donate=False, xent_chunk=chunk,
            grad_accum=args.grad_accum,
            # Load-balancing aux loss: only meaningful (and only sown) on
            # the MoE path.
            aux_loss_weight=0.01 if args.moe_every_n else 0.0,
        )

    ckpt = None
    start_step = 0
    resumed = False
    if ckpt_dir:
        from tf_operator_tpu.train.checkpoint import (
            CheckpointManager,
            resume_min_step,
        )

        ckpt = CheckpointManager(
            ckpt_dir, max_to_keep=2,
            save_interval_steps=args.checkpoint_interval,
        )
        # min_step: the operator's acked-step contract — reload() the
        # cached step list rather than resume below what is known durable
        # (the CheckpointManager follower caveat).
        state, start_step = ckpt.restore_or_init(
            state, min_step=resume_min_step()
        )
        # resumed (not the clamped start_step) gates the preemption sim:
        # with --steps 1 the clamp forces start_step back to 0, and a
        # start_step==0 guard would re-fire exit 138 forever.
        resumed = start_step > 0
        start_step = max(0, min(start_step, args.steps - 1))
        if resumed:
            print(f"dist_lm: resumed from step {start_step}", flush=True)

    # Every process generates the SAME global batch (seeded by step, so
    # resume continues the stream) and contributes its addressable shards.
    tok_spec = P("dp" if axes["dp"] > 1 else None,
                 "sp" if axes["sp"] > 1 else None)
    sharding = NamedSharding(mesh, tok_spec)

    def batch_at(step_idx: int) -> dict[str, jax.Array]:
        rng = np.random.default_rng((7, step_idx))
        start = rng.integers(0, args.vocab, (args.batch, 1))
        chain = (start + np.arange(args.seq + 1)) % args.vocab  # +1 chain
        chain = chain.astype(np.int32)
        toks, targets = chain[:, :-1], chain[:, 1:]

        def place(x):
            return jax.make_array_from_callback(
                x.shape, sharding, lambda idx: x[idx]
            )

        return {"tokens": place(toks), "targets": place(targets)}

    data_iter = None
    if args.data:
        # Real input path: this process streams ITS shard of every epoch
        # through the native pipeline; shard_batch assembles the global
        # batch from per-process rows (pure-dp only: with sp/tp the batch
        # layout is not process-row-major).
        if axes["sp"] > 1 or axes["tp"] > 1:
            raise SystemExit("--data requires sp=1 and tp=1")
        from tf_operator_tpu.parallel.sharding import shard_batch
        from tf_operator_tpu.train.data import token_dataset

        if args.batch % max(1, topo.num_processes):
            raise SystemExit(
                "global batch must be a multiple of num_processes"
            )
        local_rows = args.batch // max(1, topo.num_processes)
        data_iter = token_dataset(
            args.data, args.seq, local_rows, seed=11, loop=True,
            shard_id=topo.process_id, num_shards=max(1, topo.num_processes),
        )

        def row_stream():
            # Re-batch to EXACTLY local_rows per step, carrying epoch-tail
            # leftovers into the next step (truncating them would skip
            # records for a whole epoch) — and giving resume a stream
            # where one next() == one training step, so fast-forwarding
            # start_step steps lands precisely where training stopped.
            buf = None
            for b in data_iter:
                buf = b if buf is None else {
                    k: np.concatenate([buf[k], b[k]]) for k in b
                }
                while buf["tokens"].shape[0] >= local_rows:
                    yield {k: v[:local_rows] for k, v in buf.items()}
                    buf = {k: v[local_rows:] for k, v in buf.items()}

        import itertools

        rows = row_stream()
        first = next(rows)
        # Fail loudly on a corpus/vocab mismatch: jax gathers CLAMP
        # out-of-range ids, which would silently train on garbage.
        hi = int(first["tokens"].max())
        if hi >= args.vocab:
            raise SystemExit(
                f"--data token id {hi} >= --vocab {args.vocab}"
            )
        rows = itertools.chain([first], rows)
        for _ in range(start_step):  # resume continues, never replays
            next(rows)

        def next_data(_step_idx):
            return shard_batch(mesh, next(rows))
    else:
        next_data = batch_at

    t0 = time.perf_counter()
    metrics = None
    evict_acked = False
    for i in range(start_step, args.steps):
        state, metrics = step(state, next_data(i))
        if ckpt is not None:
            ckpt.save(i, state)
            # Progress report: the latest COMMITTED step, at zero sync
            # cost — feeds the operator's registry and staleness view.
            ckpt.maybe_ack()
            if (
                stop_event is not None
                and stop_event.is_set()
                and not evict_acked
            ):
                # Eviction checkpoint signal (the executor's graceful
                # SIGTERM): force-save the current step, drain the async
                # writer, and ack durably — the operator's eviction
                # barrier releases on this. Then KEEP training: exiting
                # here would read as success, and the pod is killed when
                # the barrier actually evicts.
                ckpt.save(i, state, force=True)
                acked = ckpt.ack()
                evict_acked = True
                print(
                    f"dist_lm: eviction signal — checkpoint durable at "
                    f"step {acked}", flush=True,
                )
        if (
            args.fail_at_step is not None
            and i == args.fail_at_step
            and not resumed
        ):
            if ckpt is not None:
                ckpt.wait()
            print(f"dist_lm: simulating preemption at step {i}", flush=True)
            import os as _os

            _os._exit(138)
        if (i + 1) % 20 == 0 or i == start_step:
            print(f"dist_lm: step {i+1} loss={float(metrics['loss']):.4f}",
                  flush=True)
    if ckpt is not None:
        ckpt.close()
    if metrics is None:
        print("dist_lm: no steps to run", flush=True)
        return 0
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    steps_run = args.steps - start_step
    tps = steps_run * args.batch * args.seq / dt
    print(
        f"dist_lm: {steps_run} steps in {dt:.1f}s ({tps:.0f} tokens/s, "
        f"mesh {axes}, ring={cfg.use_ring}, xent_chunk={chunk}), "
        f"final loss {loss:.4f}", flush=True,
    )
    if loss > args.target_loss:
        print(f"dist_lm: FAILED (loss {loss:.4f} > {args.target_loss})",
              flush=True)
        return 1
    print("dist_lm: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
