"""LM inference serving — the decode path as an operator-launched job.

Completes the LM family at the examples level: dist_lm.py trains,
serve_lm.py serves. One process loads params (from an orbax checkpoint
directory written by dist_lm.py, or quick-trains the synthetic +1-chain
task at startup so the example is self-contained), optionally shards them
for tensor-parallel decode (the shardings alone carry the parallelism —
models/transformer.py generate), and answers greedy completions over a
stdlib HTTP server:

    GET  /healthz             -> 200 once params are ready
    POST /generate            {"tokens": [[...]], "num_steps": N,
                               "temperature": T?, "top_p": P?, "seed": S?}
                              -> {"tokens": [[...]]} (generated only)

temperature=0/omitted is greedy; temperature>0 samples (nucleus-filtered
when top_p is set — top_p without temperature is a 400, mirroring
generate()'s own validation). Generation runs the jitted KV-cache decode
loop (batched single-pass prompt prefill + one-token sampling scan — one
compile per (batch, prompt_len, num_steps, temperature, top_p)
combination, so clients sweeping many distinct temperatures pay a
recompile each). ``--batch-window MS`` coalesces concurrent greedy
requests of the same shape into ONE batched decode (single-token decode
is weight-read-bound, so a batch of b amortizes the dominant HBM read
~b-fold; rows pad to power-of-two buckets to bound compile count;
sampled requests keep their per-request rng and run solo).
``--requests`` bounds the serve
loop so the process terminates like a job (the operator's Succeeded
condition); without it the server runs until SIGTERM.

The reference has no inference sample at all (its operator never runs
models); this is the TPU-native framework owning that path end to end.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def quick_train(cfg, steps: int, lr: float):
    """Train the +1-mod-vocab chain task just enough to serve verifiable
    completions (same task dist_lm.py uses for acceptance)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models.transformer import Transformer
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.train.steps import TrainState, adamw, make_lm_train_step

    mesh = create_mesh({"dp": 1}, jax.devices()[:1])
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    start = rng.integers(0, cfg.vocab_size, (8, 1))
    # Chain of seq+1 then slice: rolling the tokens would mislabel the
    # last position whenever seq % vocab != 0 (dist_lm.py does the same).
    seq = min(32, cfg.max_seq_len)
    chain = (start + np.arange(seq + 1)) % cfg.vocab_size
    batch = {
        "tokens": jnp.asarray(chain[:, :-1], jnp.int32),
        "targets": jnp.asarray(chain[:, 1:], jnp.int32),
    }
    toks = batch["tokens"]
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    tx = adamw(lr)
    state = TrainState.create(params, tx)
    step = make_lm_train_step(model, tx, mesh, seq_axis=None, donate=False)
    loss = float("nan")
    for _ in range(steps):
        state, metrics = step(state, batch)
        loss = float(metrics["loss"])
    print(f"serve_lm: quick-trained {steps} steps, loss {loss:.3f}",
          flush=True)
    return state.params


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1")
    # Model-shape flags default to dist_lm.py's defaults so the
    # train-then-serve flow works without repeating flags; when loading a
    # checkpoint from a non-default trainer run, these MUST mirror the
    # trainer's --vocab/--d-model/--layers/--seq (the restore template is
    # built from them).
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--kv-heads", type=int, default=None,
                   help="grouped-query attention: K/V heads (must divide "
                        "the 4 query heads); the decode KV cache shrinks "
                        "by the group factor")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--max-seq-len", type=int, default=128)
    p.add_argument("--checkpoint-dir", default=None,
                   help="orbax checkpoint from dist_lm.py — shape flags "
                        "must mirror the trainer's (default: quick-train "
                        "the +1-chain task at startup)")
    p.add_argument("--from-pp", type=int, default=None, metavar="PP",
                   help="the checkpoint came from dist_lm --pp PP: restore "
                        "the pipelined param tree and merge it back to the "
                        "standard layout (train/pp_lm.py merge_pp_params)")
    p.add_argument("--train-steps", type=int, default=150)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel decode over this many devices")
    p.add_argument("--int8", action="store_true",
                   help="weight-only int8 decode: quantize projections "
                        "after load (Pallas dequant-in-VMEM on TPU — "
                        "halves per-token weight reads; ops/int8_dense.py)")
    p.add_argument("--kv-int8", action="store_true",
                   help="int8 KV cache with per-(token, head) scales — "
                        "halves the cache read that dominates decode as "
                        "context grows; composes with --int8 (pure XLA, "
                        "works under --tp)")
    p.add_argument("--requests", type=int, default=None,
                   help="exit 0 after serving this many /generate calls "
                        "(job mode); default: run until SIGTERM")
    p.add_argument("--spec-k", type=int, default=0, metavar="K",
                   help="speculative decoding: a smaller DRAFT model "
                        "proposes K tokens per round, verified in one "
                        "chunked target forward (models/spec_decode.py). "
                        "Covers greedy AND sampled requests (incl. "
                        "top_p): greedy output is bit-identical to "
                        "plain greedy, sampled output follows exactly "
                        "the plain sampling distribution (a bad draft "
                        "costs speed, never correctness). 0 = off")
    p.add_argument("--spec-draft-layers", type=int, default=None,
                   help="draft depth (default max(1, --layers // 2)); "
                        "the draft trains on the same synthetic task "
                        "(quick_train), so it actually accepts")
    p.add_argument("--draft-checkpoint-dir", default=None,
                   help="orbax checkpoint for the DRAFT model (trained "
                        "at --spec-draft-layers depth, same width "
                        "flags); required when --spec-k is combined "
                        "with --checkpoint-dir")
    p.add_argument("--stream-segment", type=int, default=16, metavar="N",
                   help="segment size for streamed responses (POST "
                        '/generate with "stream": true): greedy tokens '
                        "are decoded in N-token segments through ONE "
                        "reused executable and written to the client as "
                        "NDJSON lines as each segment completes")
    p.add_argument("--prefill-chunk", type=int, default=0, metavar="N",
                   help="run streamed requests' prompt prefill in "
                        "fixed N-token chunks through one reused "
                        "executable (prefill_chunked): any prompt "
                        "length compiles nothing new. 0 = one-shot "
                        "prefill (compiles per prompt shape)")
    p.add_argument("--batch-window", type=float, default=0.0, metavar="MS",
                   help="coalesce concurrent greedy /generate requests of "
                        "the same shape for this many ms and run them as "
                        "ONE batched decode (single-token decode is "
                        "weight-read-bound, so a batch of b amortizes the "
                        "dominant HBM read ~b-fold). 0 = off")
    p.add_argument("--max-batch", type=int, default=8,
                   help="row cap per coalesced batch (--batch-window)")
    args = p.parse_args(argv)
    if args.requests is not None and args.requests < 1:
        p.error("--requests must be >= 1 (omit it to serve until SIGTERM)")
    if args.int8 and args.tp > 1:
        # Rejected up front: by the old check site the user had already
        # paid the full checkpoint restore + tp shard before the error.
        p.error("--int8 with --tp > 1 is not supported (the int8 "
                "kernel has no SPMD partitioning rule)")
    if args.spec_k:
        if args.spec_k < 1:
            p.error("--spec-k must be >= 1 (0 disables)")
        if (args.spec_draft_layers is not None
                and args.spec_draft_layers < 1):
            p.error("--spec-draft-layers must be >= 1")
        # --kv-int8 composes: speculative exactness for the int8 KV cache
        # (including the scale-buffer rollback) is pinned by
        # tests/test_spec_decode.py::test_exact_vs_greedy_cache_variants.
        # --int8 (no SPMD/quantized multi-token scoring path) and --tp
        # (no partitioning rule for the draft round) remain blocked.
        if args.int8 or args.tp > 1:
            p.error("--spec-k composes only with the plain or --kv-int8 "
                    "decode paths (not --int8/--tp; speculative "
                    "exactness is not pinned for those configurations)")
        if args.checkpoint_dir and not args.draft_checkpoint_dir:
            p.error("--spec-k with --checkpoint-dir also needs "
                    "--draft-checkpoint-dir (a draft trained at "
                    "--spec-draft-layers depth)")
    elif args.draft_checkpoint_dir:
        p.error("--draft-checkpoint-dir requires --spec-k")

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        TransformerConfig,
        generate,
        param_sharding_rules,
    )

    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=4,
        n_kv_heads=args.kv_heads,
        n_layers=args.layers, d_ff=args.d_model * 2,
        max_seq_len=args.max_seq_len, dtype=jnp.float32,
    )
    def restore_params(ckpt_dir, model_cfg, label, from_pp=None):
        """Restore trained params from a dist_lm orbax checkpoint into a
        model_cfg-shaped template — THE restore path for both the target
        and the draft, so template construction and error handling
        cannot drift. Returns None (after the standard error print) when
        the dir holds no checkpoint."""
        from tf_operator_tpu.models.transformer import Transformer
        from tf_operator_tpu.train.checkpoint import CheckpointManager
        from tf_operator_tpu.train.steps import TrainState, adamw

        ckpt = CheckpointManager(ckpt_dir)
        # Follower caveat: this directory was written by the TRAINER;
        # re-read the (orbax-cached) step list before trusting it — a
        # manager constructed while the final save was still committing
        # would otherwise serve a stale or empty step list.
        ckpt.reload()
        step = ckpt.latest_step()
        if step is None:
            print(f"serve_lm: no checkpoint in {ckpt_dir}",
                  file=sys.stderr, flush=True)
            return None
        # The trainer saved a full TrainState; restore into a matching
        # template and keep the params.
        init_params = Transformer(model_cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32)
        )["params"]
        if from_pp:
            from tf_operator_tpu.train.pp_lm import (
                merge_pp_params,
                split_pp_params,
            )

            outer, stages = split_pp_params(
                init_params, model_cfg.n_layers, from_pp
            )
            template = TrainState.create(
                {"outer": outer, "stages": stages}, adamw(args.lr)
            )
            restored = ckpt.restore(step, template).params
            restored = merge_pp_params(
                restored["outer"], restored["stages"], model_cfg.n_layers
            )
        else:
            template = TrainState.create(init_params, adamw(args.lr))
            restored = ckpt.restore(step, template).params
        print(f"serve_lm: restored {label} checkpoint step {step}"
              + (f" (merged from pp={from_pp})" if from_pp else ""),
              flush=True)
        return restored

    if args.checkpoint_dir:
        params = restore_params(
            args.checkpoint_dir, cfg, "target", from_pp=args.from_pp
        )
        if params is None:
            return 1
    else:
        params = quick_train(cfg, args.train_steps, args.lr)

    if args.tp > 1:
        from tf_operator_tpu.parallel.mesh import create_mesh
        from tf_operator_tpu.parallel.sharding import shard_params_by_rules

        mesh = create_mesh({"tp": args.tp}, jax.devices()[: args.tp])
        params = shard_params_by_rules(mesh, params, param_sharding_rules())
        print(f"serve_lm: params tp-sharded over {args.tp} devices",
              flush=True)
    if args.int8:
        from dataclasses import replace

        from tf_operator_tpu.models.transformer import quantize_decode_params

        params = quantize_decode_params(params)
        cfg = replace(cfg, int8_decode=True)
        print("serve_lm: projections quantized to int8", flush=True)
    if args.kv_int8:
        from dataclasses import replace

        cfg = replace(cfg, kv_int8=True)
        print("serve_lm: KV cache int8 (per-token/head scales)", flush=True)

    draft_cfg = draft_params = None
    if args.spec_k:
        from dataclasses import replace as _replace

        draft_cfg = _replace(
            cfg,
            n_layers=(args.spec_draft_layers
                      if args.spec_draft_layers is not None
                      else max(1, args.layers // 2)),
        )
        if args.draft_checkpoint_dir:
            draft_params = restore_params(
                args.draft_checkpoint_dir, draft_cfg, "draft"
            )
            if draft_params is None:
                return 1
        else:
            # Same synthetic task as the target: the draft genuinely
            # agrees with the target often enough to accept
            # (quick_train's data is deterministic per config shape).
            draft_params = quick_train(draft_cfg, args.train_steps, args.lr)
        print(f"serve_lm: speculative decoding on (k={args.spec_k}, "
              f"draft layers={draft_cfg.n_layers})", flush=True)

    spec_stats = {"decodes": 0, "rounds": 0, "tokens": 0}

    def decode_spec(rows, num_steps: int, temperature: float = 0.0,
                    top_p=None, sample_rng=None):
        """THE speculative decode path for greedy (direct AND coalesced)
        and sampled requests: speculative_generate when --spec-k is set
        and the speculation margin fits the cache, else None (caller
        falls back to plain generate — identical output distribution
        either way, that is the whole point). The budget formula,
        speculative call, and spec_stats (/healthz telemetry proving
        the path actually ran) live HERE only; callers hold `lock`,
        which also covers the counter updates."""
        if not (args.spec_k
                and rows.shape[1] + num_steps + args.spec_k + 1
                <= cfg.max_seq_len):
            return None
        from tf_operator_tpu.models.spec_decode import (
            speculative_generate,
        )

        out, rounds = speculative_generate(
            cfg, params, draft_cfg, draft_params, rows, num_steps,
            k=args.spec_k, temperature=temperature, top_p=top_p,
            rng=sample_rng,
        )
        spec_stats["decodes"] += 1
        spec_stats["rounds"] += int(rounds)
        spec_stats["tokens"] += num_steps
        return out

    def decode_greedy(rows, num_steps: int):
        out = decode_spec(rows, num_steps)
        if out is None:
            out = generate(cfg, params, rows, num_steps=num_steps)
        return out

    served = 0
    done = threading.Event()
    lock = threading.Lock()  # generate() calls serialized per chip

    class Coalescer:
        """Batch concurrent same-shape greedy requests into one decode.

        Rows from requests sharing (prompt_len, num_steps) that arrive
        within the window run as ONE generate() call, padded up to the
        next power-of-two row count so the set of compiled batch shapes
        stays small. Greedy-only: batching is output-invariant for
        argmax decoding, while sampled requests carry per-request rngs
        and run solo on the direct path."""

        def __init__(self, window_s: float, max_rows: int):
            self.window_s = window_s
            self.max_rows = max_rows
            self.cond = threading.Condition()
            self.pending: list[dict] = []
            self.closed = False   # loop exited: no consumer remains
            self.batches = 0      # stats for /healthz (and tests)
            self.max_rows_seen = 0

        def submit(self, prompt, num_steps: int):
            item = {
                "key": (prompt.shape[1], num_steps),
                "rows": prompt,
                "event": threading.Event(),
                "out": None,
                "err": None,
            }
            with self.cond:
                if self.closed:
                    # The batcher has exited (shutdown): failing fast
                    # beats queueing where no consumer will ever look.
                    raise RuntimeError("server shutting down")
                self.pending.append(item)
                self.cond.notify()
            if not item["event"].wait(timeout=300.0):
                raise TimeoutError("coalesced decode timed out")
            if item["err"] is not None:
                raise item["err"]
            return item["out"]

        def _key_rows(self, key) -> int:
            return sum(p["rows"].shape[0] for p in self.pending
                       if p["key"] == key)

        def _take_batch(self) -> list[dict]:
            with self.cond:
                # Wake exactly on submit()'s notify (or shutdown).
                self.cond.wait_for(
                    lambda: self.pending or done.is_set(), timeout=1.0
                )
                if not self.pending:
                    return []
                key = self.pending[0]["key"]
                # Hold the window open until the batch fills (or closes).
                self.cond.wait_for(
                    lambda: self._key_rows(key) >= self.max_rows
                    or done.is_set(),
                    timeout=self.window_s,
                )
                take: list[dict] = []
                total = 0
                for p in [p for p in self.pending if p["key"] == key]:
                    n = p["rows"].shape[0]
                    if take and total + n > self.max_rows:
                        break
                    take.append(p)
                    total += n
                for p in take:
                    self.pending.remove(p)
            return take

        def loop(self):
            # Keep draining after shutdown begins: requests already
            # queued must be answered (the direct path serves its
            # in-flight requests too), never left to hang in submit().
            try:
                self._loop()
            finally:
                # Whatever is left when the consumer stops (including a
                # crash) is answered with an error, never abandoned.
                with self.cond:
                    self.closed = True
                    leftovers, self.pending = self.pending, []
                for p in leftovers:
                    p["err"] = RuntimeError("server shutting down")
                    p["event"].set()

        def _loop(self):
            while not done.is_set() or self.pending:
                batch = self._take_batch()
                if not batch:
                    continue
                try:
                    num_steps = batch[0]["key"][1]
                    rows = jnp.concatenate(
                        [p["rows"] for p in batch], axis=0)
                    k = rows.shape[0]
                    bucket = 1
                    while bucket < k:
                        bucket *= 2
                    if bucket > k:  # pad: bounded set of batch shapes
                        rows = jnp.concatenate(
                            [rows, jnp.zeros((bucket - k, rows.shape[1]),
                                             rows.dtype)], axis=0)
                    with lock:
                        out = decode_greedy(rows, num_steps)
                    self.batches += 1
                    self.max_rows_seen = max(self.max_rows_seen, k)
                    at = 0
                    for p in batch:
                        n = p["rows"].shape[0]
                        p["out"] = out[at:at + n]
                        at += n
                except Exception as exc:  # noqa: BLE001 — a failed batch
                    # must answer its clients AND leave the loop alive.
                    for p in batch:
                        p["err"] = exc
                for p in batch:
                    p["event"].set()

    coalescer = None
    batcher_thread = None
    if args.batch_window > 0:
        coalescer = Coalescer(args.batch_window / 1e3, args.max_batch)
        batcher_thread = threading.Thread(target=coalescer.loop, daemon=True)
        batcher_thread.start()
        print(f"serve_lm: coalescing greedy requests "
              f"(window {args.batch_window:.0f} ms, "
              f"max batch {args.max_batch})", flush=True)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _json(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                payload = {"ok": True, "served": served}
                if coalescer is not None:
                    payload["coalesced_batches"] = coalescer.batches
                    payload["max_batch_rows"] = coalescer.max_rows_seen
                    payload["pending"] = len(coalescer.pending)
                if args.spec_k:
                    payload["spec_decodes"] = spec_stats["decodes"]
                    payload["spec_rounds"] = spec_stats["rounds"]
                    payload["spec_tokens"] = spec_stats["tokens"]
                self._json(200, payload)
            else:
                self._json(404, {"error": "unknown path"})

        def do_POST(self):
            nonlocal served
            if self.path != "/generate":
                self._json(404, {"error": "unknown path"})
                return
            try:
                req = json.loads(
                    self.rfile.read(int(self.headers["Content-Length"]))
                )
                prompt = jnp.asarray(req["tokens"], jnp.int32)
                num_steps = int(req.get("num_steps", 8))
                temperature = float(req.get("temperature", 0.0))
                top_p = req.get("top_p")
                if prompt.ndim != 2:
                    raise ValueError("tokens must be [batch, len]")
                kw = {}
                if temperature > 0:
                    kw = dict(
                        temperature=temperature,
                        rng=jax.random.PRNGKey(int(req.get("seed", 0))),
                    )
                if top_p is not None:
                    # Forwarded unconditionally: top_p without temperature
                    # is rejected by generate() itself (a client-visible
                    # 400), never silently dropped.
                    kw["top_p"] = float(top_p)
                if req.get("stream"):
                    # Streamed greedy decode: NDJSON, one line per
                    # segment, through the single reused segment
                    # executable (generate_segments). Runs solo — a
                    # stream is inherently per-connection, so it
                    # bypasses the coalescer and the spec path.
                    if kw:
                        # An explicit contract, like top_p-without-
                        # temperature above: silently returning buffered
                        # JSON to an NDJSON reader would wedge it.
                        raise ValueError(
                            "stream supports greedy only (no "
                            "temperature/top_p)"
                        )
                    from tf_operator_tpu.models.transformer import (
                        generate_segments,
                    )

                    # generate_segments validates segment/num_steps/cache
                    # budget EAGERLY (before any device work), so
                    # constructing it here — before headers — turns every
                    # validation error into a real 400 with one source of
                    # truth for the budget formula.
                    gen = generate_segments(
                        cfg, params, prompt, num_steps,
                        segment=max(1, args.stream_segment),
                        prefill_chunk=(args.prefill_chunk or None),
                    )
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/x-ndjson")
                    self.end_headers()
                    try:
                        while True:
                            # The chip lock covers ONLY the device work
                            # inside next(); the socket write happens
                            # unlocked, so a slow/stalled client cannot
                            # block other requests.
                            with lock:
                                try:
                                    toks = next(gen)
                                except StopIteration:
                                    break
                            line = json.dumps(
                                {"tokens": toks.tolist()}) + "\n"
                            self.wfile.write(line.encode())
                            self.wfile.flush()
                        with lock:
                            served += 1
                            if (args.requests is not None
                                    and served >= args.requests):
                                done.set()
                    except Exception as exc:  # noqa: BLE001
                        # Headers are out: a 400 is impossible. Close the
                        # connection (the client sees a truncated stream)
                        # and log server-side.
                        print(f"serve_lm: stream aborted: {exc!r}",
                              file=sys.stderr, flush=True)
                    return
                if coalescer is not None and not kw:
                    out = coalescer.submit(prompt, num_steps)
                elif not kw:
                    with lock:
                        out = decode_greedy(prompt, num_steps)
                else:
                    # Sampled requests (with or without top_p) also try
                    # the distribution-preserving speculative path: the
                    # accept/residual scheme targets the tempered —
                    # and, when requested, nucleus-filtered — softmax
                    # exactly. top_p-without-temperature still reaches
                    # plain generate, whose 400 defines that contract.
                    with lock:
                        out = None
                        if "temperature" in kw:
                            out = decode_spec(
                                prompt, num_steps,
                                temperature=kw["temperature"],
                                top_p=kw.get("top_p"),
                                sample_rng=kw["rng"],
                            )
                        if out is None:
                            out = generate(
                                cfg, params, prompt,
                                num_steps=num_steps, **kw
                            )
                self._json(200, {"tokens": out.tolist()})
            except Exception as exc:  # noqa: BLE001 — client-visible error
                self._json(400, {"error": repr(exc)})
                return
            # Budget accounting under the lock: concurrent handler threads
            # would otherwise lose increments and never trip the budget.
            with lock:
                served += 1
                if args.requests is not None and served >= args.requests:
                    done.set()

    server = ThreadingHTTPServer((args.host, args.port), Handler)
    print(f"serve_lm: listening on {server.server_address[0]}:"
          f"{server.server_address[1]}", flush=True)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    threading.Thread(target=server.serve_forever, daemon=True).start()
    done.wait()
    server.shutdown()
    if batcher_thread is not None:
        # The batcher loop drains queued requests after done is set, but
        # its thread (and the handler threads waiting in submit()) are
        # daemons — main must hold the process open until the drain
        # finishes and the answers have gone out, or it is theater.
        # Joining the THREAD (not polling the queue) covers the final
        # in-flight batch: _take_batch pops items before generate()
        # runs, so an empty queue proves nothing while a decode (or its
        # cold compile) is still executing.
        import time as _time

        batcher_thread.join(timeout=30.0)
        _time.sleep(0.2)  # let unblocked handlers write their responses
    print(f"serve_lm: done ({served} request(s) served)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
