"""LM inference serving — the decode path as an operator-launched job.

Completes the LM family at the examples level: dist_lm.py trains,
serve_lm.py serves. One process loads params (from an orbax checkpoint
directory written by dist_lm.py, or quick-trains the synthetic +1-chain
task at startup so the example is self-contained), optionally shards them
for tensor-parallel decode (the shardings alone carry the parallelism —
models/transformer.py generate), and answers greedy completions over a
stdlib HTTP server:

    GET  /healthz             -> 200 once params are ready; liveness +
                              readiness: ``draining: true`` during the
                              SIGTERM bounded drain, ``dead: true`` when
                              the restart budget is spent, plus slot
                              occupancy / queue depth / ttft_p99_s and
                              the fleet ``replica`` id — the probe
                              payload fleet/membership.py routes from
    POST /generate            {"tokens": [[...]], "num_steps": N,
                               "temperature": T?, "top_p": P?, "seed": S?,
                               "json_schema"|"regex"|"choices": ...?,
                               "stop": [...]?, "logprobs": true?, "n": N?}
                              -> {"tokens": [[...]]} (generated only;
                              constrained requests add "finish_reason",
                              "logprobs" rows under --logprobs-k, and
                              an n-best "choices" list — see
                              docs/constrained-decoding.md)

temperature=0/omitted is greedy; temperature>0 samples (nucleus-filtered
when top_p is set — top_p without temperature is a 400, mirroring
generate()'s own validation). Two serving engines (``--engine``):

- ``continuous`` (default): the slot-based continuous-batching engine
  (tf_operator_tpu/serve/): requests join a preallocated slot tensor
  whenever a slot is free, ONE compiled decode step advances every
  active slot per iteration, and slots retire independently on
  num_steps (or a request's ``eos_id``). Sampled requests batch too
  (per-slot rng reproduces their solo output exactly), occupancy
  changes never recompile, and token-budgeted chunked prefill
  (``--prefill-chunk`` + ``--prefill-budget``) interleaves long prompts
  with decode so TTFT stays short without stalling running requests.
  KV storage is BLOCK-PAGED by default (``--kv-block``-token blocks,
  ``--kv-pool-blocks`` pool): admission charges actual lengths rather
  than max-seq-len rows, identical block-aligned prompt prefixes share
  physical blocks copy-on-write and skip their prefill, and
  ``--kv-dense`` falls back to the PR-5 dense slot tensor. ``--kv-int8``
  composes with BOTH layouts (paged: int8 blocks + per-block scale
  sidecar pools riding the same tables). ``--kv-attend pallas`` swaps
  the paged decode attend for the block-table-walking pallas kernel
  (per-lane-bounded HBM traffic, bit-identical to the gather default;
  docs/serving.md "Paged-attention kernel"). ``--tp N``
  runs the SAME engine SPMD over an N-device mesh: params tp-sharded by
  the training rules, KV storage head-sharded, one compiled step
  driving the whole slice (composes with ``--kv-paged``/``--kv-dense``;
  output stays bit-identical to solo decode). ``--dp M`` makes the mesh
  2-D (tp x dp, pod-scale): per-slot state and the paged pool's block
  axis ALSO shard over dp — each dp shard owns max-batch/M slots and
  its own block extent, admission routes each request to one shard, and
  the same single compiled step drives the whole 2-D slice, still
  bit-identical (docs/serving.md "Pod-scale decode"). ``--spec-k K`` turns
  every decode iteration into a BATCH-WIDE speculative round: each
  slot drafts K tokens and one batched K+1-position verify scores
  them all, per-slot accept counters advancing slots DIFFERENT
  numbers of tokens per round — greedy output stays bit-identical to
  plain greedy, sampled slots keep their exact sampling law, and the
  two round executables never recompile across occupancy or accept
  variation (composes with ``--tp`` and ``--kv-int8``). STRUCTURED
  DECODING (serve/constrain.py, docs/constrained-decoding.md): a
  request's ``json_schema``/``regex``/``choices`` field compiles at
  enqueue into a token-level DFA bound into a fixed-shape device
  constraint pool (``--constrain-rows``); the SAME compiled step masks
  every slot's logits through the pool (row 0 = always-allow for free
  slots), so any constrained/unconstrained mix — under spec decode,
  paged/dense/kv8, tp — never recompiles. ``stop`` sequences match
  host-side (excluded from output), ``logprobs: true`` returns
  per-token top-K rows (``--logprobs-k``), and ``n > 1`` fans one
  sampled prompt into n candidate slots sharing ONE prefill via the
  exact-prefix join. Invalid grammars are a typed 400
  (``invalid_grammar``), before any device work.
  ``/debug/serve`` exposes the scheduler snapshot and ``/metrics`` the
  ``tpu_serve_*`` families. On SIGTERM the engine DRAINS: admitted
  requests finish (bounded by ``--drain-timeout`` — stragglers resolve
  with partial output + a flag), queued ones fail fast with a 503 — no
  hung sockets.

  The continuous engine always serves SUPERVISED (serve/resilience.py):
  requests expire in queue after ``--queue-ttl`` (typed 408) or resolve
  with their PARTIAL generation + ``"deadline_exceeded": true`` when
  ``--decode-deadline`` (or a per-request ``"deadline_s"`` field)
  passes; the queue is bounded (``--queue-limit``, typed 503 +
  Retry-After above it); low free KV blocks cap admitted max_tokens
  (``--degraded-blocks``/``--degraded-max-tokens``, response flagged
  ``"degraded"``); and a watchdog rebuilds a crashed or stalled engine
  (``--watchdog-stall``, ``--max-restarts``, ``--restart-backoff``) and
  REPLAYS in-flight requests — greedy replays are bit-identical to an
  uninterrupted run. Every error response carries ``code``/
  ``retryable``/``detail`` (and Retry-After where meaningful) so a
  router can tell retryable replica failures from request errors.
  ``--faults``/``TPU_SERVE_FAULTS`` arm the seeded fault-injection
  points (serve/faultinject.py) for chaos drills.
- ``coalesce``: the legacy lock-step path. Direct per-request decode
  (one compile per (batch, prompt_len, num_steps, temperature, top_p)
  combination), optionally with ``--batch-window MS`` coalescing
  concurrent same-shape greedy requests into one padded batched decode
  (serve/coalesce.py). Selected automatically only under
  ``--batch-window`` (the window IS the coalesce policy — --spec-k,
  --int8, and --tp are all continuous-engine modes now); kept
  selectable for the exactness matrix and as the spec bench baseline.

``--requests`` bounds the serve
loop so the process terminates like a job (the operator's Succeeded
condition); without it the server runs until SIGTERM.

The reference has no inference sample at all (its operator never runs
models); this is the TPU-native framework owning that path end to end.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def quick_train(cfg, steps: int, lr: float):
    """Train the +1-mod-vocab chain task just enough to serve verifiable
    completions (same task dist_lm.py uses for acceptance)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models.transformer import Transformer
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.train.steps import TrainState, adamw, make_lm_train_step

    mesh = create_mesh({"dp": 1}, jax.devices()[:1])
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    start = rng.integers(0, cfg.vocab_size, (8, 1))
    # Chain of seq+1 then slice: rolling the tokens would mislabel the
    # last position whenever seq % vocab != 0 (dist_lm.py does the same).
    seq = min(32, cfg.max_seq_len)
    chain = (start + np.arange(seq + 1)) % cfg.vocab_size
    batch = {
        "tokens": jnp.asarray(chain[:, :-1], jnp.int32),
        "targets": jnp.asarray(chain[:, 1:], jnp.int32),
    }
    toks = batch["tokens"]
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    tx = adamw(lr)
    state = TrainState.create(params, tx)
    step = make_lm_train_step(model, tx, mesh, seq_axis=None, donate=False)
    loss = float("nan")
    for _ in range(steps):
        state, metrics = step(state, batch)
        loss = float(metrics["loss"])
    print(f"serve_lm: quick-trained {steps} steps, loss {loss:.3f}",
          flush=True)
    return state.params


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("TPU_SERVE_PORT") or 0),
                   help="listen port (default $TPU_SERVE_PORT — the "
                        "fleet controller injects it per replica — "
                        "else an ephemeral port)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--replica-id",
                   default=os.environ.get("TPU_SERVE_REPLICA_ID", ""),
                   help="fleet replica identity (default "
                        "$TPU_SERVE_REPLICA_ID): stamped on /healthz "
                        "and every typed error payload so the router "
                        "attributes failures without reverse-mapping "
                        "ports")
    p.add_argument("--role", choices=("decode", "prefill"),
                   default=os.environ.get("TPU_SERVE_ROLE") or "decode",
                   help="replica role (default $TPU_SERVE_ROLE): "
                        "'prefill' serves ONLY POST /prefill — prompt "
                        "prefill exported as shipped-KV block-pool "
                        "rows for a disaggregated fleet's decode pool "
                        "(serve/disagg.py; --kv-block must match the "
                        "decode pool's). 'decode' (or unset) is the "
                        "ordinary serving process")
    # Model-shape flags default to dist_lm.py's defaults so the
    # train-then-serve flow works without repeating flags; when loading a
    # checkpoint from a non-default trainer run, these MUST mirror the
    # trainer's --vocab/--d-model/--layers/--seq (the restore template is
    # built from them).
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--kv-heads", type=int, default=None,
                   help="grouped-query attention: K/V heads (must divide "
                        "the 4 query heads); the decode KV cache shrinks "
                        "by the group factor")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--max-seq-len", type=int, default=128)
    p.add_argument("--checkpoint-dir", default=None,
                   help="orbax checkpoint from dist_lm.py — shape flags "
                        "must mirror the trainer's (default: quick-train "
                        "the +1-chain task at startup)")
    p.add_argument("--from-pp", type=int, default=None, metavar="PP",
                   help="the checkpoint came from dist_lm --pp PP: restore "
                        "the pipelined param tree and merge it back to the "
                        "standard layout (train/pp_lm.py merge_pp_params)")
    p.add_argument("--train-steps", type=int, default=150)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel decode over this many devices: "
                        "params tp-sharded by the training rules, and "
                        "under the continuous engine the slot KV "
                        "storage (paged pool or dense tensor) is "
                        "head-sharded over the mesh so ONE compiled "
                        "step drives the whole slice (composes with "
                        "--kv-paged/--kv-dense/--kv-int8/--spec-k; "
                        "--int8 params replicate — the dequant kernel "
                        "has no SPMD rule)")
    p.add_argument("--dp", type=int, default=1,
                   help="pod-scale decode (composes with --tp; "
                        "tp*dp devices): ALSO shard the slot axis — "
                        "per-slot state and the paged pool's block "
                        "axis split over a second mesh axis, each dp "
                        "shard owning max-batch/dp slots and its own "
                        "block extent, ONE compiled step driving the "
                        "whole 2-D slice (requires --dp to divide "
                        "--max-batch; continuous engine only)")
    p.add_argument("--int8", action="store_true",
                   help="weight-only int8 decode: quantize projections "
                        "after load (Pallas dequant-in-VMEM on TPU — "
                        "halves per-token weight reads; ops/int8_dense.py)")
    p.add_argument("--kv-int8", action="store_true",
                   help="int8 KV cache with per-(token, head) scales — "
                        "halves the cache read that dominates decode as "
                        "context grows. Composes with --int8, --tp, "
                        "--spec-k, AND the paged pool (int8 blocks + "
                        "per-block scale sidecar pools riding the same "
                        "block tables)")
    p.add_argument("--requests", type=int, default=None,
                   help="exit 0 after serving this many /generate calls "
                        "(job mode); default: run until SIGTERM")
    p.add_argument("--spec-k", type=int, default=0, metavar="K",
                   help="speculative decoding: a smaller DRAFT model "
                        "proposes K tokens per round, verified in one "
                        "chunked target forward (models/spec_decode.py). "
                        "Under the continuous engine (default) this is "
                        "BATCH-WIDE: every slot drafts+verifies per "
                        "round with per-slot accept counters, so slots "
                        "advance different amounts (serve/engine.py). "
                        "Covers greedy AND sampled requests (incl. "
                        "top_p): greedy output is bit-identical to "
                        "plain greedy, sampled output follows exactly "
                        "the plain sampling distribution (a bad draft "
                        "costs speed, never correctness). Composes "
                        "with --tp and --kv-int8; prompt + num_steps + "
                        "K + 1 must fit --max-seq-len. 0 = off")
    p.add_argument("--spec-draft-layers", type=int, default=None,
                   help="draft depth (default max(1, --layers // 2)); "
                        "the draft trains on the same synthetic task "
                        "(quick_train), so it actually accepts")
    p.add_argument("--draft-checkpoint-dir", default=None,
                   help="orbax checkpoint for the DRAFT model (trained "
                        "at --spec-draft-layers depth, same width "
                        "flags); required when --spec-k is combined "
                        "with --checkpoint-dir")
    p.add_argument("--logprobs-k", type=int, default=0, metavar="K",
                   help="per-token top-K logprobs in /generate responses "
                        '(opt-in per request via "logprobs": true). '
                        "Engine-constructor static — the compiled step's "
                        "output arity — so it is a flag, not a request "
                        "field; continuous engine only, and mutually "
                        "exclusive with --spec-k (verify rounds emit "
                        "whole windows, not per-step rows). 0 = off")
    p.add_argument("--constrain-rows", type=int, default=128, metavar="N",
                   help="constraint-pool rows (serve/constrain.py): the "
                        "fixed-shape device tables compiled grammar "
                        "programs (json_schema/regex/choices request "
                        "fields) bind into. Row 0 is the always-allow "
                        "garbage row; a program needs n_states "
                        "contiguous rows. HBM cost: rows x vocab bool + "
                        "rows x vocab int32 (~5 bytes/cell)")
    p.add_argument("--stream-segment", type=int, default=16, metavar="N",
                   help="segment size for streamed responses (POST "
                        '/generate with "stream": true): greedy tokens '
                        "are decoded in N-token segments through ONE "
                        "reused executable and written to the client as "
                        "NDJSON lines as each segment completes")
    p.add_argument("--prefill-chunk", type=int, default=0, metavar="N",
                   help="run streamed requests' prompt prefill in "
                        "fixed N-token chunks through one reused "
                        "executable (prefill_chunked): any prompt "
                        "length compiles nothing new. 0 = one-shot "
                        "prefill (compiles per prompt shape)")
    p.add_argument("--batch-window", type=float, default=0.0, metavar="MS",
                   help="legacy engine: coalesce concurrent greedy "
                        "/generate requests of the same shape for this "
                        "many ms and run them as ONE batched decode "
                        "(single-token decode is weight-read-bound, so a "
                        "batch of b amortizes the dominant HBM read "
                        "~b-fold). Implies --engine coalesce. 0 = off")
    p.add_argument("--max-batch", type=int, default=8,
                   help="decode slots of the continuous engine / row cap "
                        "per coalesced batch (--batch-window)")
    p.add_argument("--engine", choices=("continuous", "coalesce"),
                   default=None,
                   help="serving engine: 'continuous' = slot-based "
                        "continuous batching (tf_operator_tpu/serve/ — "
                        "in-flight join/retire, sampled requests batch "
                        "too, zero recompiles across occupancy; "
                        "--tp/--spec-k/--int8/--kv-int8 all compose); "
                        "'coalesce' = the legacy direct/batch-window "
                        "path, kept selectable for the exactness "
                        "matrix and the spec bench baseline. Default: "
                        "continuous unless --batch-window (the window "
                        "IS the coalesce policy)")
    p.add_argument("--prefill-budget", type=int, default=256,
                   metavar="TOKENS",
                   help="continuous engine: max prompt tokens prefilled "
                        "per serving-loop iteration while slots are "
                        "decoding (with --prefill-chunk, long prompts "
                        "stream in across iterations instead of stalling "
                        "every active request)")
    p.add_argument("--kv-paged", dest="kv_paged", action="store_true",
                   default=True,
                   help="continuous engine: block-paged KV cache with "
                        "copy-on-write shared-prefix reuse (the "
                        "default) — admission becomes 'free slot AND "
                        "enough free blocks for prompt + max_tokens', "
                        "so memory scales with ACTUAL lengths and "
                        "identical prompt prefixes prefill once")
    p.add_argument("--kv-dense", dest="kv_paged", action="store_false",
                   help="escape hatch: the PR-5 dense slot tensor "
                        "(every slot pre-pays max-seq-len rows; no "
                        "prefix sharing)")
    p.add_argument("--kv-block", type=int, default=64, metavar="TOKENS",
                   help="paged KV cache block size in tokens "
                        "(--max-seq-len must divide evenly)")
    p.add_argument("--kv-attend", choices=("gather", "pallas"),
                   default="gather",
                   help="paged decode attention path: 'gather' (the "
                        "default and the bit-identity oracle — pool "
                        "blocks gathered dense, XLA einsum) or "
                        "'pallas' (ops/paged_attention.py — walks the "
                        "block table directly so per-step HBM traffic "
                        "is bounded by actual lane lengths; pinned "
                        "bit-identical to gather; requires --kv-paged "
                        "and a geometry inside the kernel's VMEM "
                        "budget, and runs INTERPRETED off-TPU)")
    p.add_argument("--prefix-advertise", type=int, default=32,
                   metavar="N",
                   help="hot prefix-cache entries advertised on "
                        "/healthz for fleet-global prefix routing "
                        "(paged continuous engine; MRU first; 0 "
                        "disables advertisement — the replica still "
                        "answers /prefix/<digest> pulls)")
    p.add_argument("--kv-pool-blocks", type=int, default=None,
                   metavar="N",
                   help="paged KV pool size in blocks, incl. the pinned "
                        "garbage block (default: the dense cache's "
                        "byte budget — max-batch x max-seq-len/kv-block "
                        "+ 1; raise max-batch past what the dense "
                        "layout could hold and cap memory here instead)")
    p.add_argument("--host-tier-bytes", type=int, default=0,
                   metavar="BYTES",
                   help="host-RAM KV tier byte budget "
                        "(docs/kv-tiering.md): evicted prefix-cache "
                        "entries spill here as wire payloads and "
                        "admission restores them (session resume "
                        "without re-prefill); also answers fleet "
                        "/prefix/<digest> pulls and advertises "
                        "tier_prefixes on /healthz. 0 (default) "
                        "disables the tier — accounting is then "
                        "bit-identical to pre-tier serving. The tier "
                        "outlives watchdog rebuilds: spilled sessions "
                        "survive an engine restart")
    p.add_argument("--tier-prefetch", type=int, default=1,
                   metavar="0|1",
                   help="async host-tier prefetch at enqueue for "
                        "requests carrying a session key (the prefix "
                        "upload overlaps queue wait); 0 restores only "
                        "at admission")
    res = p.add_argument_group(
        "resilience (continuous engine; 0 disables a knob)"
    )
    res.add_argument("--queue-ttl", type=float, default=30.0, metavar="S",
                     help="expire requests still queued after this many "
                          "seconds with a typed 408 + Retry-After "
                          "(they never cost device work)")
    res.add_argument("--decode-deadline", type=float, default=120.0,
                     metavar="S",
                     help="default end-to-end deadline: past it a "
                          "request resolves with its PARTIAL generation "
                          "and \"deadline_exceeded\": true instead of "
                          "hanging (per-request \"deadline_s\" "
                          "overrides)")
    res.add_argument("--watchdog-stall", type=float, default=10.0,
                     metavar="S",
                     help="serving-loop heartbeat silence that triggers "
                          "an engine teardown + rebuild + in-flight "
                          "replay; must exceed the worst-case single "
                          "device op INCLUDING a cold prefill compile")
    res.add_argument("--max-restarts", type=int, default=3,
                     help="consecutive watchdog restarts before the "
                          "replica declares itself dead and drains "
                          "typed 503s (the budget resets once a rebuilt "
                          "engine completes a request)")
    res.add_argument("--restart-backoff", type=float, default=0.25,
                     metavar="S",
                     help="base of the exponential backoff between "
                          "watchdog restarts")
    res.add_argument("--queue-limit", type=int, default=None, metavar="N",
                     help="bounded queue watermark: above it new "
                          "requests shed with a typed 503 + Retry-After "
                          "(reject-newest; default 8x --max-batch)")
    res.add_argument("--degraded-blocks", type=float, default=0.1,
                     metavar="FRAC",
                     help="degraded mode: when the free KV-block "
                          "fraction drops below this, admitted "
                          "max_tokens is capped (paged engines only)")
    res.add_argument("--degraded-max-tokens", type=int, default=32,
                     metavar="N",
                     help="the degraded-mode max_tokens cap (responses "
                          "carry \"degraded\": true)")
    res.add_argument("--drain-timeout", type=float, default=30.0,
                     metavar="S",
                     help="bound the SIGTERM drain: past it the "
                          "remaining admitted requests resolve with "
                          "partial output + the drain flag instead of "
                          "holding shutdown")
    res.add_argument("--faults", default=None, metavar="SPEC",
                     help="arm seeded fault-injection points (chaos "
                          "drills): e.g. 'step_raise@40,step_stall@90:5'"
                          " — see serve/faultinject.py; default: the "
                          "TPU_SERVE_FAULTS env var")
    res.add_argument("--fault-seed", type=int, default=0,
                     help="seed for probabilistic fault entries")
    p.add_argument("--trace-capacity", type=int, default=8192,
                   metavar="SPANS",
                   help="bounded ring of request-scoped data-plane "
                        "trace spans exported at /debug/traces "
                        "(Chrome-trace JSON; evictions counted in "
                        "tpu_trace_spans_dropped_total). 0 disables "
                        "tracing entirely")
    args = p.parse_args(argv)
    # --batch-window is the ONLY legacy selector left: --tp became a
    # continuous-engine mode in PR 10, and --spec-k/--int8 joined it in
    # PR 15 (batch-wide speculative decode rides the slot engine's
    # per-lane counters; --int8 weights are a params-tree property the
    # engine never branches on). The window is inherently the coalesce
    # policy, so it keeps selecting that path.
    legacy_flags = [flag for flag, on in (
        ("--batch-window", args.batch_window > 0),
    ) if on]
    if args.engine == "continuous" and legacy_flags:
        p.error(f"--engine continuous does not compose with "
                f"{'/'.join(legacy_flags)} (the window IS the coalesce "
                f"policy — use --engine coalesce)")
    if args.engine is None:
        args.engine = "coalesce" if legacy_flags else "continuous"
    if args.dp > 1:
        if args.engine != "continuous":
            p.error("--dp > 1 needs --engine continuous (the dp slot "
                    "slices exist only in the continuous engine)")
        if args.max_batch % args.dp:
            p.error("--dp must divide --max-batch (each dp shard owns "
                    "an equal slot slice)")
        if args.spec_k:
            p.error("--dp does not compose with --spec-k yet (the "
                    "pod-scale bit-identity pins cover the plain "
                    "engine; the spec engine's dp placement is "
                    "unvalidated)")
    if args.role == "prefill":
        bad = [flag for flag, on in (
            ("--spec-k", bool(args.spec_k)),
            ("--int8", args.int8),
            ("--kv-int8", args.kv_int8),
            ("--batch-window", args.batch_window > 0),
            ("--tp", args.tp > 1),
            ("--dp", args.dp > 1),
        ) if on]
        if bad:
            p.error(f"--role prefill does not compose with "
                    f"{'/'.join(bad)} (a prefill replica runs only the "
                    "solo dense prefill and ships its rows)")
        if args.max_seq_len % args.kv_block:
            p.error("--role prefill needs --kv-block to divide "
                    "--max-seq-len (the shipped rows are block-aligned "
                    "pool rows for the decode pool)")
    if args.prefill_budget < 1:
        p.error("--prefill-budget must be >= 1")
    if args.requests is not None and args.requests < 1:
        p.error("--requests must be >= 1 (omit it to serve until SIGTERM)")
    if args.spec_k:
        if args.spec_k < 1:
            p.error("--spec-k must be >= 1 (0 disables)")
        if (args.spec_draft_layers is not None
                and args.spec_draft_layers < 1):
            p.error("--spec-draft-layers must be >= 1")
        # --kv-int8 composes (dense AND paged: the spec×kv8 exactness is
        # pinned by tests/test_spec_decode.py and the engine matrix in
        # tests/test_serve_engine.py), and --tp composes (the engine
        # shards the draft by the same rules — tools/serve_tp_check.py
        # pins the spec/tp leg). --int8 stays blocked: speculative
        # decoding rejects int8_decode trees, same contract as solo
        # speculative_generate.
        if args.int8:
            p.error("--spec-k does not compose with --int8 "
                    "(speculative decoding rejects int8_decode param "
                    "trees; quantize after choosing a decode strategy)")
        if args.checkpoint_dir and not args.draft_checkpoint_dir:
            p.error("--spec-k with --checkpoint-dir also needs "
                    "--draft-checkpoint-dir (a draft trained at "
                    "--spec-draft-layers depth)")
    elif args.draft_checkpoint_dir:
        p.error("--draft-checkpoint-dir requires --spec-k")
    if args.logprobs_k:
        if args.logprobs_k < 0:
            p.error("--logprobs-k must be >= 0")
        if args.spec_k:
            p.error("--logprobs-k does not compose with --spec-k "
                    "(verify rounds emit accept-dependent windows, not "
                    "per-step logit rows)")
        if args.engine != "continuous":
            p.error("--logprobs-k requires --engine continuous")
    if args.constrain_rows < 1:
        p.error("--constrain-rows must be >= 1")

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        TransformerConfig,
        generate,
        param_sharding_rules,
    )

    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=4,
        n_kv_heads=args.kv_heads,
        n_layers=args.layers, d_ff=args.d_model * 2,
        max_seq_len=args.max_seq_len, dtype=jnp.float32,
    )
    def restore_params(ckpt_dir, model_cfg, label, from_pp=None):
        """Restore trained params from a dist_lm orbax checkpoint into a
        model_cfg-shaped template — THE restore path for both the target
        and the draft, so template construction and error handling
        cannot drift. Returns None (after the standard error print) when
        the dir holds no checkpoint."""
        from tf_operator_tpu.models.transformer import Transformer
        from tf_operator_tpu.train.checkpoint import CheckpointManager
        from tf_operator_tpu.train.steps import TrainState, adamw

        ckpt = CheckpointManager(ckpt_dir)
        # Follower caveat: this directory was written by the TRAINER;
        # re-read the (orbax-cached) step list before trusting it — a
        # manager constructed while the final save was still committing
        # would otherwise serve a stale or empty step list.
        ckpt.reload()
        step = ckpt.latest_step()
        if step is None:
            print(f"serve_lm: no checkpoint in {ckpt_dir}",
                  file=sys.stderr, flush=True)
            return None
        # The trainer saved a full TrainState; restore into a matching
        # template and keep the params.
        init_params = Transformer(model_cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32)
        )["params"]
        if from_pp:
            from tf_operator_tpu.train.pp_lm import (
                merge_pp_params,
                split_pp_params,
            )

            outer, stages = split_pp_params(
                init_params, model_cfg.n_layers, from_pp
            )
            template = TrainState.create(
                {"outer": outer, "stages": stages}, adamw(args.lr)
            )
            restored = ckpt.restore(step, template).params
            restored = merge_pp_params(
                restored["outer"], restored["stages"], model_cfg.n_layers
            )
        else:
            template = TrainState.create(init_params, adamw(args.lr))
            restored = ckpt.restore(step, template).params
        print(f"serve_lm: restored {label} checkpoint step {step}"
              + (f" (merged from pp={from_pp})" if from_pp else ""),
              flush=True)
        return restored

    if args.checkpoint_dir:
        params = restore_params(
            args.checkpoint_dir, cfg, "target", from_pp=args.from_pp
        )
        if params is None:
            return 1
    else:
        params = quick_train(cfg, args.train_steps, args.lr)

    if args.int8:
        from dataclasses import replace

        from tf_operator_tpu.models.transformer import quantize_decode_params

        params = quantize_decode_params(params)
        cfg = replace(cfg, int8_decode=True)
        print("serve_lm: projections quantized to int8", flush=True)
    mesh = None
    if args.tp > 1 or args.dp > 1:
        from tf_operator_tpu.parallel.mesh import create_mesh
        from tf_operator_tpu.parallel.sharding import shard_params_by_rules

        # --dp adds the second mesh axis: params REPLICATE over it
        # (every dp shard decodes its own slot slice with the full
        # model) while the engine shards slot state and the pool's
        # block axis over it — serve/sharding.py slot_spec/leaf_spec.
        need = args.tp * args.dp
        axes = {"tp": args.tp}
        if args.dp > 1:
            axes["dp"] = args.dp
        mesh = create_mesh(axes, jax.devices()[:need])
        # int8 trees replicate (the dequant kernel has no SPMD
        # partitioning rule — serve/engine.py applies the same policy);
        # tp still shards the KV storage and drives one compiled step
        # across the slice.
        params = shard_params_by_rules(
            mesh, params,
            {} if args.int8 else param_sharding_rules(),
        )
        print(f"serve_lm: params {'replicated (int8)' if args.int8 else 'tp-sharded'} "
              f"over {need} devices"
              + (f" (tp {args.tp} x dp {args.dp})" if args.dp > 1
                 else ""), flush=True)
    if args.kv_int8:
        from dataclasses import replace

        cfg = replace(cfg, kv_int8=True)
        print("serve_lm: KV cache int8 (per-token/head scales)", flush=True)

    draft_cfg = draft_params = None
    if args.spec_k:
        from dataclasses import replace as _replace

        draft_cfg = _replace(
            cfg,
            n_layers=(args.spec_draft_layers
                      if args.spec_draft_layers is not None
                      else max(1, args.layers // 2)),
        )
        if args.draft_checkpoint_dir:
            draft_params = restore_params(
                args.draft_checkpoint_dir, draft_cfg, "draft"
            )
            if draft_params is None:
                return 1
        else:
            # Same synthetic task as the target: the draft genuinely
            # agrees with the target often enough to accept
            # (quick_train's data is deterministic per config shape).
            draft_params = quick_train(draft_cfg, args.train_steps, args.lr)
        print(f"serve_lm: speculative decoding on (k={args.spec_k}, "
              f"draft layers={draft_cfg.n_layers})", flush=True)

    spec_stats = {"decodes": 0, "rounds": 0, "tokens": 0}

    def decode_spec(rows, num_steps: int, temperature: float = 0.0,
                    top_p=None, sample_rng=None):
        """THE speculative decode path for greedy (direct AND coalesced)
        and sampled requests: speculative_generate when --spec-k is set
        and the speculation margin fits the cache, else None (caller
        falls back to plain generate — identical output distribution
        either way, that is the whole point). The budget formula,
        speculative call, and spec_stats (/healthz telemetry proving
        the path actually ran) live HERE only; callers hold `lock`,
        which also covers the counter updates."""
        if not (args.spec_k
                and rows.shape[1] + num_steps + args.spec_k + 1
                <= cfg.max_seq_len):
            return None
        from tf_operator_tpu.models.spec_decode import (
            speculative_generate,
        )

        out, rounds = speculative_generate(
            cfg, params, draft_cfg, draft_params, rows, num_steps,
            k=args.spec_k, temperature=temperature, top_p=top_p,
            rng=sample_rng,
        )
        spec_stats["decodes"] += 1
        spec_stats["rounds"] += int(rounds)
        spec_stats["tokens"] += num_steps
        return out

    def decode_greedy(rows, num_steps: int):
        out = decode_spec(rows, num_steps)
        if out is None:
            out = generate(cfg, params, rows, num_steps=num_steps)
        return out

    from tf_operator_tpu.runtime.tracing import SERVE_TRACER, mint_request_id

    if args.trace_capacity != SERVE_TRACER.capacity:
        SERVE_TRACER.set_capacity(args.trace_capacity)
        print(f"serve_lm: trace ring "
              f"{'disabled' if args.trace_capacity <= 0 else args.trace_capacity}",
              flush=True)

    served = 0
    done = threading.Event()
    lock = threading.Lock()  # generate() calls serialized per chip

    if args.replica_id:
        # Typed error payloads (serve/resilience.py) self-report this id
        # from here on; /healthz mirrors it below.
        from tf_operator_tpu.serve.resilience import set_replica_id

        set_replica_id(args.replica_id)

    if args.role == "prefill":
        # Dedicated prefill replica (disaggregated serving): no decode
        # engine, no slots — prompt prefill only, exported as shipped-KV
        # wire payloads for the fleet's decode pool. The controller
        # injects TPU_SERVE_ROLE=prefill into "{serve}-p{i}" children;
        # SIGTERM drains exactly like the decode path (readiness
        # withdrawn first, in-flight prefills finish).
        import time

        from tf_operator_tpu.serve.disagg import (
            PrefillServer,
            PrefillWorker,
        )

        worker = PrefillWorker(
            cfg, params, prefill_chunk=args.prefill_chunk or None,
            kv_block=args.kv_block,
        )
        pserver = PrefillServer(
            worker, replica_id=args.replica_id or "prefill",
            host=args.host, port=args.port,
        ).start()
        print(f"serve_lm: PREFILL replica "
              f"{args.replica_id or '(anonymous)'} on "
              f"{pserver.endpoint} (kv_block={args.kv_block}, "
              f"chunk={args.prefill_chunk or 'one-shot'})", flush=True)
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: done.set())
        done.wait()
        pserver.begin_drain()
        drain_deadline = time.monotonic() + args.drain_timeout
        while (worker.queue_depth or worker.active_slots) \
                and time.monotonic() < drain_deadline:
            time.sleep(0.05)
        pserver.stop()
        print(f"serve_lm: prefill replica drained "
              f"({worker.requests_done} prompts, "
              f"{worker.tokens_prefilled} tokens shipped)", flush=True)
        return 0

    coalescer = None
    batcher_thread = None
    engine_sched = None
    if args.engine == "continuous":
        from tf_operator_tpu.serve.engine import ContinuousEngine
        from tf_operator_tpu.serve.faultinject import FaultInjector
        from tf_operator_tpu.serve.resilience import (
            EngineSupervisor,
            ResilienceConfig,
        )

        kv_paged = args.kv_paged
        if kv_paged and args.max_seq_len % args.kv_block:
            p.error(f"--max-seq-len {args.max_seq_len} must be a "
                    f"multiple of --kv-block {args.kv_block} "
                    "(or use --kv-dense)")
        if args.faults is not None:
            faults = FaultInjector(args.faults, seed=args.fault_seed)
        else:
            faults = FaultInjector.from_env()
        if faults.enabled:
            print(f"serve_lm: FAULT INJECTION armed: "
                  f"{faults.snapshot()['armed']}", flush=True)
        res_cfg = ResilienceConfig(
            queue_ttl_s=args.queue_ttl or None,
            decode_deadline_s=args.decode_deadline or None,
            watchdog_stall_s=args.watchdog_stall or None,
            max_restarts=args.max_restarts,
            restart_backoff_s=args.restart_backoff,
            queue_limit=(args.queue_limit if args.queue_limit is not None
                         else 8 * args.max_batch) or None,
            degraded_free_block_frac=args.degraded_blocks or 0.0,
            degraded_max_tokens=args.degraded_max_tokens,
            drain_timeout_s=args.drain_timeout or None,
        )

        # ONE process-lifetime host tier, attached to every engine the
        # factory builds: a watchdog rebuild loses the HBM pool but NOT
        # the spilled sessions — the new generation restores them on
        # demand (docs/kv-tiering.md).
        host_tier = None
        if kv_paged and args.host_tier_bytes > 0:
            from tf_operator_tpu.serve.tier import HostTier
            host_tier = HostTier(args.host_tier_bytes)

        def engine_factory():
            # The watchdog rebuilds through here: SAME cfg/params/mesh
            # every time, so a replayed greedy request is bit-identical
            # to an uninterrupted run — the rebuilt engine reconstructs
            # the tp layout (re-places the KV pools head-sharded) from
            # the captured mesh, at tp>1 exactly as at tp=1. --spec-k
            # rides along: the rebuilt engine re-seeds its draft cache
            # at each replay's join, so replays stay bit-identical.
            eng = ContinuousEngine(
                cfg, params, max_slots=args.max_batch,
                prefill_chunk=(args.prefill_chunk or None),
                kv_paged=kv_paged, kv_block=args.kv_block,
                kv_blocks=args.kv_pool_blocks,
                kv_attend=args.kv_attend if kv_paged else "gather",
                faults=faults, mesh=mesh,
                spec_k=args.spec_k, draft_cfg=draft_cfg,
                draft_params=draft_params,
                constrain_rows=args.constrain_rows,
                logprobs_k=args.logprobs_k,
            )
            if kv_paged:
                # Inside the factory so a watchdog rebuild keeps the
                # flags (the supervisor rebuilds through here).
                # Retention matches the advertisement width: every
                # digest the replica advertises stays exportable and
                # exact-joinable after its request completes.
                eng.prefix_advertise_max = args.prefix_advertise
                eng.prefix_retain_max = args.prefix_advertise
                eng.host_tier = host_tier
            return eng

        # ONE process-lifetime constraint compiler (like the host tier):
        # the program LRU survives watchdog rebuilds, and every replica
        # generation compiles against the same vocab closure. The demo
        # vocab is the identity charset (token id i = chr(i)) — real
        # deployments pass the tokenizer's decoded token strings.
        from tf_operator_tpu.serve.constrain import (
            ConstraintCompiler,
            default_vocab,
        )
        constrainer = ConstraintCompiler(default_vocab(cfg.vocab_size))

        engine_sched = EngineSupervisor(
            engine_factory,
            resilience=res_cfg,
            faults=faults,
            prefill_tokens_per_step=args.prefill_budget,
            # Streaming requests bypass the engine and share the chip:
            # one lock serializes both decode paths.
            device_lock=lock,
            tier_prefetch=bool(args.tier_prefetch),
            constrainer=constrainer,
        )
        kv_desc = (
            f"paged kv ({args.kv_block}-token blocks, "
            f"{engine_sched.engine.kv_blocks} block pool)"
            if kv_paged else "dense kv"
        )
        if host_tier is not None:
            kv_desc += (f", host tier "
                        f"{args.host_tier_bytes >> 20 or 1} MiB"
                        f"{' +prefetch' if args.tier_prefetch else ''}")
        if mesh is not None:
            kv_desc += f", tp {args.tp} (SPMD mesh, kv head-sharded)"
            if args.dp > 1:
                kv_desc += (f" x dp {args.dp} (slots + pool blocks "
                            f"dp-sharded)")
        if args.spec_k:
            kv_desc += (f", spec k={args.spec_k} "
                        f"(draft {draft_cfg.n_layers} layer(s))")
        kv_desc += f", constrain pool {args.constrain_rows} rows"
        if args.logprobs_k:
            kv_desc += f", logprobs top-{args.logprobs_k}"
        print(f"serve_lm: continuous batching "
              f"(slots {args.max_batch}, {kv_desc}, prefill chunk "
              f"{args.prefill_chunk or 'one-shot'}, prefill budget "
              f"{args.prefill_budget} tok/iter; deadlines "
              f"queue={args.queue_ttl or 'off'}s "
              f"decode={args.decode_deadline or 'off'}s, watchdog "
              f"{args.watchdog_stall or 'off'}s x{args.max_restarts}, "
              f"queue limit {res_cfg.queue_limit or 'off'}, drain "
              f"{args.drain_timeout or 'unbounded'}s)", flush=True)
    elif args.batch_window > 0:
        from tf_operator_tpu.serve.coalesce import Coalescer

        def coalesced_decode(rows, num_steps: int):
            with lock:
                return decode_greedy(rows, num_steps)

        coalescer = Coalescer(
            args.batch_window / 1e3, args.max_batch, coalesced_decode, done
        )
        batcher_thread = threading.Thread(target=coalescer.loop, daemon=True)
        batcher_thread.start()
        print(f"serve_lm: coalescing greedy requests "
              f"(window {args.batch_window:.0f} ms, "
              f"max batch {args.max_batch})", flush=True)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _json(self, code: int, payload: dict,
                  headers: dict | None = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                from tf_operator_tpu.serve.httpapi import (
                    readiness_payload,
                )

                # Liveness/readiness split (PR 9): done set = the
                # SIGTERM bounded drain is in flight — alive (ok stays
                # true) but taking no NEW traffic; dead = the restart
                # budget is spent and the replica wants replacing.
                payload = readiness_payload(
                    engine_sched, draining=done.is_set(),
                    replica=args.replica_id,
                    max_slots=(args.max_batch
                               if engine_sched is not None else None),
                )
                payload["served"] = served
                payload["engine"] = args.engine
                if coalescer is not None:
                    payload["coalesced_batches"] = coalescer.batches
                    payload["max_batch_rows"] = coalescer.max_rows_seen
                    payload["pending"] = len(coalescer.pending)
                if args.spec_k and engine_sched is not None:
                    # Continuous engine: batch-wide speculation stats
                    # from the live engine (accept rate included).
                    payload["spec"] = engine_sched.engine.spec_debug()
                elif args.spec_k:
                    payload["spec_decodes"] = spec_stats["decodes"]
                    payload["spec_rounds"] = spec_stats["rounds"]
                    payload["spec_tokens"] = spec_stats["tokens"]
                self._json(200, payload)
            elif self.path == "/debug/serve" and engine_sched is not None:
                # The same payload serve/httpapi.py mounts on an operator
                # ApiServer — one shape for dashboards either way.
                self._json(200, engine_sched.debug_snapshot())
            elif self.path == "/debug/traces":
                # The data-plane trace ring (queue wait / prefill /
                # decode intervals / watchdog restarts, keyed by
                # request_id) as Chrome-trace JSON; a fleet router or
                # tpuctl trace merges several replicas' exports.
                self._json(200, SERVE_TRACER.export_doc())
            elif self.path == "/metrics":
                from tf_operator_tpu.runtime.metrics import REGISTRY

                body = REGISTRY.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif (self.path.startswith("/prefix/")
                    and engine_sched is not None):
                # Fleet-global prefix reuse: export one hot prefix
                # entry (named by its chained per-block digest, the
                # same chain /healthz advertises) in the shipped-KV
                # wire format. The fleet router pulls this onto a
                # replica that misses the prefix; a stale digest
                # answers the typed prefix_not_found — the puller
                # degrades to local prefill.
                from tf_operator_tpu.serve.resilience import (
                    error_payload,
                    http_status_of,
                )

                digest = self.path[len("/prefix/"):]
                try:
                    shipment = engine_sched.export_prefix(digest)
                except Exception as exc:  # noqa: BLE001 — typed out
                    payload = error_payload(exc)
                    payload["replica"] = args.replica_id
                    self._json(http_status_of(exc), payload)
                    return
                self._json(200, {"shipment": shipment,
                                 "replica": args.replica_id})
            else:
                self._json(404, {"error": "unknown path"})

        def do_POST(self):
            nonlocal served
            if self.path != "/generate":
                self._json(404, {"error": "unknown path"})
                return
            try:
                req = json.loads(
                    self.rfile.read(int(self.headers["Content-Length"]))
                )
                prompt = jnp.asarray(req["tokens"], jnp.int32)
                num_steps = int(req.get("num_steps", 8))
                temperature = float(req.get("temperature", 0.0))
                top_p = req.get("top_p")
                if prompt.ndim != 2:
                    raise ValueError("tokens must be [batch, len]")
                kw = {}
                if temperature > 0:
                    kw = dict(
                        temperature=temperature,
                        rng=jax.random.PRNGKey(int(req.get("seed", 0))),
                    )
                if top_p is not None:
                    # Forwarded unconditionally: top_p without temperature
                    # is rejected by generate() itself (a client-visible
                    # 400), never silently dropped.
                    kw["top_p"] = float(top_p)
                # Structured-decoding request fields are continuous-
                # engine only (the constraint pool and the host stop/
                # logprob bookkeeping live in the scheduler): anywhere
                # else they are a 400, never a silent no-op.
                structured = (
                    any(req.get(k) is not None for k in
                        ("json_schema", "regex", "choices", "stop"))
                    or bool(req.get("logprobs"))
                    or int(req.get("n", 1)) != 1
                )
                if req.get("stream"):
                    if structured:
                        raise ValueError(
                            "stream does not compose with json_schema/"
                            "regex/choices/stop/logprobs/n (use the "
                            "continuous engine's buffered path)"
                        )
                    # Streamed greedy decode: NDJSON, one line per
                    # segment, through the single reused segment
                    # executable (generate_segments). Runs solo — a
                    # stream is inherently per-connection, so it
                    # bypasses the coalescer and the spec path.
                    if kw:
                        # An explicit contract, like top_p-without-
                        # temperature above: silently returning buffered
                        # JSON to an NDJSON reader would wedge it.
                        raise ValueError(
                            "stream supports greedy only (no "
                            "temperature/top_p)"
                        )
                    from tf_operator_tpu.models.transformer import (
                        generate_segments,
                    )

                    # generate_segments validates segment/num_steps/cache
                    # budget EAGERLY (before any device work), so
                    # constructing it here — before headers — turns every
                    # validation error into a real 400 with one source of
                    # truth for the budget formula.
                    gen = generate_segments(
                        cfg, params, prompt, num_steps,
                        segment=max(1, args.stream_segment),
                        prefill_chunk=(args.prefill_chunk or None),
                    )
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/x-ndjson")
                    self.end_headers()
                    try:
                        while True:
                            # The chip lock covers ONLY the device work
                            # inside next(); the socket write happens
                            # unlocked, so a slow/stalled client cannot
                            # block other requests.
                            with lock:
                                try:
                                    toks = next(gen)
                                except StopIteration:
                                    break
                            line = json.dumps(
                                {"tokens": toks.tolist()}) + "\n"
                            self.wfile.write(line.encode())
                            self.wfile.flush()
                        with lock:
                            served += 1
                            if (args.requests is not None
                                    and served >= args.requests):
                                done.set()
                    except Exception as exc:  # noqa: BLE001
                        # Headers are out: a 400 is impossible. Close the
                        # connection (the client sees a truncated stream)
                        # and log server-side.
                        print(f"serve_lm: stream aborted: {exc!r}",
                              file=sys.stderr, flush=True)
                    return
                if engine_sched is not None:
                    # Continuous engine: greedy AND sampled requests join
                    # the slot batch (per-slot rng reproduces each row's
                    # solo output exactly). Multi-row prompts split into
                    # per-row requests — rows are independent streams to
                    # a slot engine — and reassemble in order. An
                    # optional "eos_id" retires a row early; an optional
                    # "deadline_s" overrides --decode-deadline per
                    # request.
                    import numpy as _np

                    from tf_operator_tpu.serve.scheduler import (
                        ServeRequest,
                    )

                    eos_id = req.get("eos_id")
                    deadline_s = req.get("deadline_s")
                    # Request identity for tracing: client-supplied
                    # (body field or X-Request-Id header) or minted
                    # here; multi-row fan-outs suffix the row index so
                    # each slot request stays individually traceable
                    # while the response keys on the parent id.
                    rid = (req.get("request_id")
                           or self.headers.get("X-Request-Id")
                           or mint_request_id())

                    # Structured/constrained decoding: at most one of
                    # json_schema/regex/choices (the compiler's typed
                    # 400 owns the message for conflicts/bad grammars),
                    # plus multi-token "stop" sequences, per-token
                    # "logprobs" (needs --logprobs-k), and "n" best-of
                    # candidates (docs/constrained-decoding.md).
                    constrain = {
                        k: req[k]
                        for k in ("json_schema", "regex", "choices")
                        if req.get(k) is not None
                    } or None
                    stop = req.get("stop")
                    want_logprobs = bool(req.get("logprobs"))
                    n_best = int(req.get("n", 1))
                    if n_best < 1:
                        raise ValueError(f"n={n_best} must be >= 1")
                    if n_best > 1:
                        if prompt.shape[0] != 1:
                            raise ValueError(
                                "n > 1 requires a single-row prompt "
                                "(candidates fan out over slots)"
                            )
                        if temperature <= 0:
                            raise ValueError(
                                "n > 1 requires temperature > 0 "
                                "(greedy candidates would be identical)"
                            )
                        if n_best > args.max_batch:
                            raise ValueError(
                                f"n={n_best} exceeds slot capacity "
                                f"{args.max_batch}"
                            )

                    shipment = None
                    if req.get("shipped_kv") is not None:
                        # Disaggregated prefill: verify the shipped
                        # payload (chained digests + row checksum + the
                        # request's own prompt) BEFORE it reaches the
                        # scheduler — a mismatch RAISES the typed
                        # ship_failed (rendered by the generic handler
                        # below; the disagg router re-prefills on it).
                        # Single-row only: a shipment prefills ONE
                        # prompt.
                        from tf_operator_tpu.serve.disagg import (
                            decode_shipment,
                        )
                        from tf_operator_tpu.serve.resilience import (
                            ShipFailed,
                        )

                        if prompt.shape[0] != 1:
                            raise ShipFailed(
                                "shipped_kv serves single-row "
                                "requests only"
                            )
                        shipment = decode_shipment(
                            req["shipped_kv"], expect_tokens=prompt[0],
                        )

                    def _row(i):
                        # n-best candidates ride the SAME fan-out as
                        # multi-row prompts: candidate j is row 0's
                        # request at seed+j (distinct sampled streams)
                        # — identical prompts exact-prefix-join in the
                        # paged pool, so n candidates pay ONE prefill.
                        r = ServeRequest(
                            _np.asarray(
                                prompt[0:1] if n_best > 1
                                else prompt[i:i + 1]
                            ), num_steps,
                            temperature=temperature,
                            top_p=(None if top_p is None
                                   else float(top_p)),
                            # Per-row seed offset: rows are independent
                            # slot requests, and seed+i keeps multi-row
                            # sampled rows distinct (the legacy batched
                            # generate drew independent rows from one
                            # key) while row 0 still reproduces the
                            # single-row request for the same seed.
                            seed=int(req.get("seed", 0)) + i,
                            eos_id=(None if eos_id is None
                                    else int(eos_id)),
                            deadline_s=(None if deadline_s is None
                                        else float(deadline_s)),
                            request_id=(rid if i == 0
                                        else f"{rid}.{i}"),
                            # A session key pre-warms the host KV tier
                            # at enqueue (--tier-prefetch,
                            # docs/kv-tiering.md); each row prefetches
                            # against its own prompt chain.
                            session=req.get("session"),
                            # Single-row contract enforced above, so
                            # the shipment always belongs to row 0.
                            shipment=shipment,
                            constrain=constrain,
                            stop=stop,
                            logprobs=want_logprobs,
                        )
                        return engine_sched.submit_request(r)

                    fanout = (n_best if n_best > 1
                              else prompt.shape[0])
                    if fanout == 1:
                        rows = [_row(0)]
                    else:
                        # Rows decode concurrently (submit blocks per
                        # request; serializing them would run the batch
                        # one row at a time). Pool capped at the slot
                        # count: extra threads could only park in the
                        # queue anyway, and an uncapped pool would spawn
                        # one OS thread per row of an arbitrary request.
                        from concurrent.futures import ThreadPoolExecutor

                        with ThreadPoolExecutor(
                            min(fanout, args.max_batch)
                        ) as ex:
                            rows = list(ex.map(_row, range(fanout)))
                    out = [list(r.out) for r in rows]
                    payload = {"tokens": out, "request_id": rid}
                    if any(r.finish_reason for r in rows):
                        # Why each stream ended: "length" | "eos" |
                        # "grammar_complete" | "stop_sequence" (None
                        # for deadline-cut partials — those carry
                        # deadline_exceeded below instead).
                        payload["finish_reason"] = [
                            r.finish_reason for r in rows
                        ]
                    if want_logprobs:
                        payload["logprobs"] = [
                            r.logprob_rows for r in rows
                        ]
                    if n_best > 1:
                        # Candidate view of the same rows: one entry
                        # per seed, ordered. "tokens" above stays the
                        # raw per-slot list so existing readers (and
                        # the fleet response assembler) are unchanged.
                        payload["choices"] = [
                            {
                                "tokens": list(r.out),
                                "seed": int(req.get("seed", 0)) + j,
                                "finish_reason": r.finish_reason,
                            }
                            for j, r in enumerate(rows)
                        ]
                    if req.get("timing"):
                        # Opt-in compact latency attribution per row:
                        # queue/prefill/decode ms + ITL summary (the
                        # span-level story lives at /debug/traces).
                        payload["timing"] = [r.timing() for r in rows]
                    if any(r.deadline_exceeded for r in rows):
                        # Partial generations: the deadline (or bounded
                        # drain) cut these rows short — the tokens are
                        # real, the flag says they are not all of them.
                        payload["deadline_exceeded"] = [
                            r.deadline_exceeded for r in rows
                        ]
                        payload["timeout_cause"] = [
                            r.timeout_cause for r in rows
                        ]
                    if any(r.degraded for r in rows):
                        # Degraded admission capped max_tokens while KV
                        # blocks were scarce.
                        payload["degraded"] = [r.degraded for r in rows]
                    self._json(200, payload)
                    with lock:
                        served += 1
                        if (args.requests is not None
                                and served >= args.requests):
                            done.set()
                    return
                elif structured:
                    raise ValueError(
                        "json_schema/regex/choices/stop/logprobs/n "
                        "require --engine continuous"
                    )
                elif coalescer is not None and not kw:
                    out = coalescer.submit(prompt, num_steps)
                elif not kw:
                    with lock:
                        out = decode_greedy(prompt, num_steps)
                else:
                    # Sampled requests (with or without top_p) also try
                    # the distribution-preserving speculative path: the
                    # accept/residual scheme targets the tempered —
                    # and, when requested, nucleus-filtered — softmax
                    # exactly. top_p-without-temperature still reaches
                    # plain generate, whose 400 defines that contract.
                    with lock:
                        out = None
                        if "temperature" in kw:
                            out = decode_spec(
                                prompt, num_steps,
                                temperature=kw["temperature"],
                                top_p=kw.get("top_p"),
                                sample_rng=kw["rng"],
                            )
                        if out is None:
                            out = generate(
                                cfg, params, prompt,
                                num_steps=num_steps, **kw
                            )
                self._json(200, {
                    "tokens": out if isinstance(out, list) else out.tolist()
                })
            except Exception as exc:  # noqa: BLE001 — client-visible error
                from tf_operator_tpu.serve.resilience import (
                    ServeError,
                    error_payload,
                )

                if isinstance(exc, ServeError):
                    # Typed serving failure: 503/408 + {code, retryable,
                    # detail} (+ Retry-After) — a router can tell a
                    # draining/dead replica from a bad request, and
                    # nothing ever hangs a socket.
                    headers = {}
                    if exc.retry_after_s is not None:
                        headers["Retry-After"] = str(
                            max(1, int(round(exc.retry_after_s)))
                        )
                    self._json(exc.http_status, error_payload(exc),
                               headers)
                elif isinstance(exc, TimeoutError):
                    # The server ran out of time, not the request out of
                    # validity: retryable 503, never a bad_request.
                    self._json(503, {
                        "error": repr(exc), "code": "timeout",
                        "retryable": True, "detail": repr(exc),
                    })
                else:
                    self._json(400, error_payload(exc) | {
                        "code": "bad_request", "error": repr(exc),
                    })
                return
            # Budget accounting under the lock: concurrent handler threads
            # would otherwise lose increments and never trip the budget.
            with lock:
                served += 1
                if args.requests is not None and served >= args.requests:
                    done.set()

    server = ThreadingHTTPServer((args.host, args.port), Handler)
    print(f"serve_lm: listening on {server.server_address[0]}:"
          f"{server.server_address[1]}", flush=True)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    threading.Thread(target=server.serve_forever, daemon=True).start()
    done.wait()
    # NOT server.shutdown() yet: /healthz keeps answering through the
    # drain with ``draining: true`` (the PR 9 readiness split) so a
    # fleet router deregisters this replica on the flag instead of
    # eating refused probes — and in-flight /generate handlers keep
    # their sockets. New requests are refused typed by the draining
    # engine underneath.
    if engine_sched is not None:
        # The ckpt/eviction SIGTERM drain: admitted requests finish their
        # decode, queued ones are answered 503 NOW — and main holds the
        # process open (handler threads are daemons) until the loop
        # confirms the drain, plus a beat for the response writes.
        import time as _time

        # The drain itself is bounded by --drain-timeout inside the
        # loop (stragglers resolve with partial output + the drain
        # flag); the join budget just needs to outlast it.
        engine_sched.stop(timeout=max(60.0, (args.drain_timeout or 0) + 30.0))
        _time.sleep(0.2)
        print(f"serve_lm: engine drained "
              f"({engine_sched.requests_done} request(s), "
              f"{engine_sched.tokens_generated} token(s))", flush=True)
    if batcher_thread is not None:
        # The batcher loop drains queued requests after done is set, but
        # its thread (and the handler threads waiting in submit()) are
        # daemons — main must hold the process open until the drain
        # finishes and the answers have gone out, or it is theater.
        # Joining the THREAD (not polling the queue) covers the final
        # in-flight batch: _take_batch pops items before generate()
        # runs, so an empty queue proves nothing while a decode (or its
        # cold compile) is still executing.
        import time as _time

        batcher_thread.join(timeout=30.0)
        _time.sleep(0.2)  # let unblocked handlers write their responses
    server.shutdown()
    print(f"serve_lm: done ({served} request(s) served)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
