#!/usr/bin/env python
"""Fast gang-scheduler smoke: runs the `scheduler`-marked tests in
isolation (scheduler unit + integration suite plus the gang-admission
chaos cases on both cluster backends) — the ~5s loop for iterating on
tf_operator_tpu/scheduler/ without paying for the whole tier-1 run.

    python tools/sched_smoke.py            # the smoke subset
    python tools/sched_smoke.py -k quota   # extra pytest args pass through

Exit code is pytest's. CI wires this as the pre-merge gate for scheduler
changes; the same tests also run (unmarked-slow, so by default) inside the
tier-1 command in ROADMAP.md.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [
        sys.executable, "-m", "pytest",
        "tests/test_scheduler.py", "tests/test_chaos.py",
        "-m", "scheduler",
        "-q", "-p", "no:cacheprovider",
        *args,
    ]
    return subprocess.call(cmd, cwd=REPO_ROOT, env=env)


if __name__ == "__main__":
    raise SystemExit(main())
