"""Control-plane scale benchmark: N synthetic TPUJobs through a real
TPUJobController against the in-memory cluster with a watch-driven fake
kubelet.

What it proves (ISSUE 3 / docs/performance.md): the controller's read path
is O(result), not O(world) — indexed informer lookups serve every pod/
service/node read, so a steady-state reconcile wave issues ZERO API `list`
calls for those kinds, and p99 sync latency stays flat at 10x the
reference's O(100)-job design target (tf_job_design_doc.md:32-36).

The kubelet here is deliberately watch-driven (it never lists): pods are
tracked from watch deltas and advanced Pending → Running → Succeeded via
update_status, so the `tpu_api_requests_total{verb="list"}` counters
measure only what the CONTROL PLANE issues.

Phases:
  1. start controller, wait for informer sync     (initial LISTs land here)
  2. submit N jobs, drive all of them to Running  (creation wave)
  3. hold Running for --steady-seconds            (steady-state window:
     reconcile waves run; list counters for pods/services/nodes must not
     move)
  4. release the kubelet hold, drive all jobs to Succeeded

Emits one BENCH-style JSON line (the same shape bench.py emits), plus a
full result dict on --verbose. Used by tests/test_scale.py (100-job tier-1
smoke, 1000-job slow+scale tier) and picked up by bench.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from tf_operator_tpu.api import constants
from tf_operator_tpu.cli.genjob import synthetic_job
from tf_operator_tpu.controller import tpujob_controller as tc_mod
from tf_operator_tpu.controller.jobcontroller import JobControllerConfig
from tf_operator_tpu.controller.tpujob_controller import TPUJobController
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ApiError, Conflict, NotFound
from tf_operator_tpu.runtime.memcluster import InMemoryCluster
from tf_operator_tpu.runtime.metrics import API_REQUESTS_TOTAL

# The kinds whose steady-state reads must be cache-served (the acceptance
# bar): pod/service reads in every sync, node reads in every health poll.
CACHED_KINDS = (objects.PODS, objects.SERVICES, objects.NODES)
VERBS = ("create", "get", "list", "update", "update_status", "patch",
         "delete", "watch")


class WatchKubelet(threading.Thread):
    """Advances pods Pending → Running → Succeeded from watch deltas only.

    Never calls list: its world model is built purely from the pod watch
    stream, so every `list` the API counters record during the run is the
    control plane's. Scheduling-gated pods are left alone (the store would
    reject the phase write anyway); they advance once the gang release
    ungates them. Works over ANY ClusterClient (watch + update_status) —
    the wire E2E (tests/test_kubeclient.py) runs it over KubeClusterClient.
    """

    def __init__(self, client: Any, stop: threading.Event) -> None:
        super().__init__(daemon=True, name="watch-kubelet")
        self.client = client
        self.stop_event = stop
        self.hold_running = threading.Event()  # set = do NOT finish pods
        self._running: dict[str, dict[str, Any]] = {}  # name -> last seen pod
        self.running_count = 0

    def _advance(self, pod: dict[str, Any]) -> None:
        name = objects.name_of(pod)
        phase = objects.pod_phase(pod)
        gated = bool(pod.get("spec", {}).get("schedulingGates"))
        try:
            if phase == objects.PENDING and not gated:
                objects.set_pod_phase(pod, objects.RUNNING)
                self.client.update_status(objects.PODS, pod)
            elif phase == objects.RUNNING:
                if self.hold_running.is_set():
                    if name not in self._running:
                        self._running[name] = pod
                        self.running_count = len(self._running)
                else:
                    objects.set_pod_phase(pod, objects.SUCCEEDED)
                    objects.set_container_terminated(
                        pod, constants.DEFAULT_CONTAINER_NAME, 0
                    )
                    self.client.update_status(objects.PODS, pod)
                    self._running.pop(name, None)
        except (Conflict, NotFound):
            # Raced a controller write or a deletion: the store broadcasts
            # another MODIFIED with the fresh RV (or the pod is gone);
            # the next event retries — exactly a kubelet's model.
            pass
        except ApiError:
            pass

    def release(self) -> None:
        """Stop holding: finish everything currently Running, and let new
        Running pods complete immediately."""
        self.hold_running.clear()
        for pod in list(self._running.values()):
            self._advance(pod)
        self._running.clear()

    def run(self) -> None:
        watch = self.client.watch(objects.PODS, None)
        while not self.stop_event.is_set():
            event = watch.next(timeout=0.1)
            if event is None:
                continue
            if event.type == "DELETED":
                self._running.pop(objects.name_of(event.object), None)
                continue
            self._advance(event.object)
        watch.stop()


def _api_snapshot() -> dict[tuple[str, str], float]:
    kinds = set(CACHED_KINDS) | {objects.TPUJOBS, objects.PDBS,
                                 objects.CONFIGMAPS, objects.EVENTS}
    return {
        (verb, kind): API_REQUESTS_TOTAL.value(verb=verb, kind=kind)
        for verb in VERBS
        for kind in kinds
    }


def _api_delta(
    t0: dict[tuple[str, str], float]
) -> dict[str, dict[str, int]]:
    out: dict[str, dict[str, int]] = {}
    for (verb, kind), before in t0.items():
        d = int(API_REQUESTS_TOTAL.value(verb=verb, kind=kind) - before)
        if d:
            out.setdefault(verb, {})[kind] = d
    return out


def run_bench(
    jobs: int = 1000,
    workers: int = 1,
    threadiness: int = 4,
    reconcile_period: float = 2.0,
    steady_seconds: float = 6.0,
    timeout: float = 300.0,
) -> dict[str, Any]:
    client = InMemoryCluster()
    controller = TPUJobController(
        client,
        JobControllerConfig(
            reconcile_period=reconcile_period,
            # Resync re-lists by design; park it outside the run so the
            # list counters isolate the reconcile path itself.
            informer_resync=3600.0,
            threadiness=threadiness,
        ),
    )
    stop = threading.Event()
    sync_baseline = tc_mod.SYNC_SECONDS.snapshot()
    threading.Thread(target=controller.run, args=(stop,), daemon=True).start()
    kubelet = WatchKubelet(client, stop)
    kubelet.hold_running.set()
    kubelet.start()

    result: dict[str, Any] = {
        "jobs": jobs, "workers": workers, "threadiness": threadiness,
        "reconcile_period_s": reconcile_period,
    }
    max_queue_depth = 0

    def _sample_queue() -> None:
        nonlocal max_queue_depth
        max_queue_depth = max(max_queue_depth, len(controller.queue))

    try:
        for informer in (controller.job_informer, controller.pod_informer,
                         controller.service_informer):
            if not informer.wait_synced(30):
                raise RuntimeError("informers never synced")
        run_t0 = _api_snapshot()

        # -- creation wave ---------------------------------------------------
        t0 = time.monotonic()
        for i in range(jobs):
            client.create(
                objects.TPUJOBS,
                synthetic_job(f"bench-{i}", "default", workers, None, None),
            )
        result["submit_seconds"] = round(time.monotonic() - t0, 3)

        want_pods = jobs * workers
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            _sample_queue()
            if kubelet.running_count >= want_pods:
                break
            time.sleep(0.2)
        result["time_to_all_running_s"] = round(time.monotonic() - t0, 3)
        if kubelet.running_count < want_pods:
            result["error"] = (
                f"only {kubelet.running_count}/{want_pods} pods Running "
                f"after {timeout}s"
            )
            return result

        # -- steady-state window ---------------------------------------------
        steady_t0 = _api_snapshot()
        steady_sync_t0 = tc_mod.SYNCS_TOTAL.value(result="ok")
        steady_end = time.monotonic() + steady_seconds
        while time.monotonic() < steady_end:
            _sample_queue()
            time.sleep(0.1)
        steady = _api_delta(steady_t0)
        result["steady_seconds"] = steady_seconds
        result["steady_syncs"] = int(
            tc_mod.SYNCS_TOTAL.value(result="ok") - steady_sync_t0
        )
        result["steady_api_requests"] = steady
        result["steady_list_calls"] = {
            kind: steady.get("list", {}).get(kind, 0) for kind in CACHED_KINDS
        }

        # -- drain to Succeeded ----------------------------------------------
        kubelet.release()

        def succeeded_count() -> int:
            n = 0
            for job in client.list(objects.TPUJOBS, "default"):
                for cond in job.get("status", {}).get("conditions", []):
                    if cond["type"] == "Succeeded" and cond["status"] == "True":
                        n += 1
                        break
            return n

        done = 0
        while time.monotonic() < deadline:
            _sample_queue()
            done = succeeded_count()
            if done == jobs:
                break
            time.sleep(0.3)
        result["succeeded"] = done
        result["total_seconds"] = round(time.monotonic() - t0, 3)
        if done < jobs:
            result["error"] = f"only {done}/{jobs} jobs Succeeded"

        # Workqueue drain: once the fleet is terminal nothing should keep
        # keys ready — a leak in the delayed-heap coalescing would show up
        # here as a queue that never empties (the old 100-job scale test's
        # assertion, carried over).
        drain_deadline = time.monotonic() + 15
        drained = False
        while time.monotonic() < drain_deadline:
            if len(controller.queue) == 0:
                drained = True
                break
            time.sleep(0.1)
        result["queue_drained"] = drained
        result["final_queue_depth"] = len(controller.queue)

        result["p50_sync_ms"] = round(
            tc_mod.SYNC_SECONDS.quantile(0.5, since=sync_baseline) * 1e3, 3
        )
        result["p99_sync_ms"] = round(
            tc_mod.SYNC_SECONDS.quantile(0.99, since=sync_baseline) * 1e3, 3
        )
        result["max_queue_depth"] = max_queue_depth
        result["enqueues_coalesced"] = controller.queue.coalesced
        result["api_requests"] = _api_delta(run_t0)
        wedged = [
            k for k in list(controller.expectations._store)
            if not controller.expectations.satisfied(k)
        ]
        result["wedged_expectations"] = wedged
        return result
    finally:
        stop.set()
        time.sleep(0.3)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="bench_control_plane", description=__doc__)
    p.add_argument("--jobs", type=int, default=1000)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--threadiness", type=int, default=4)
    p.add_argument("--reconcile-period", type=float, default=2.0)
    p.add_argument("--steady-seconds", type=float, default=6.0)
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--verbose", action="store_true",
                   help="also print the full result dict")
    args = p.parse_args(argv)

    result = run_bench(
        jobs=args.jobs,
        workers=args.workers,
        threadiness=args.threadiness,
        reconcile_period=args.reconcile_period,
        steady_seconds=args.steady_seconds,
        timeout=args.timeout,
    )
    if args.verbose:
        print(json.dumps(result, indent=2), file=sys.stderr)

    steady_lists = sum(result.get("steady_list_calls", {}).values())
    # The BENCH-style line (same shape bench.py emits). vs_baseline: the
    # reference design target is O(100) jobs; value 1.0 at 100 jobs.
    line = {
        "metric": "control_plane_jobs_sustained",
        "value": result.get("succeeded", 0),
        "unit": "jobs",
        "vs_baseline": round(result.get("succeeded", 0) / 100.0, 3),
        "p50_sync_ms": result.get("p50_sync_ms"),
        "p99_sync_ms": result.get("p99_sync_ms"),
        "total_seconds": result.get("total_seconds"),
        "steady_list_calls": steady_lists,
        "steady_syncs": result.get("steady_syncs"),
        "max_queue_depth": result.get("max_queue_depth"),
        "enqueues_coalesced": result.get("enqueues_coalesced"),
    }
    if "error" in result:
        line["error"] = result["error"]
    print(json.dumps(line), flush=True)
    return 1 if "error" in result else 0


if __name__ == "__main__":
    sys.exit(main())
