#!/usr/bin/env python
"""SPMD tensor-parallel decode exactness check: the continuous engine on
a >1-device mesh, bit-identical to solo generate, with zero decode
recompiles — the multi-chip half of the PR-5 exactness matrix, runnable
anywhere via the XLA host-device trick.

Proves, at ``--tp`` devices (default 2, forced as CPU host devices
BEFORE jax imports so the check needs no hardware):

- engine greedy output == solo ``generate`` with the SAME tp-sharded
  params, bit-for-bit, for every cell of {dense, paged} x {one-shot,
  chunked prefill}, across join/retire mid-decode, slot reuse, sampled
  (temperature + seeded rng) slots, and — paged — shared-prefix
  admission;
- the KV storage is REALLY sharded: each device's addressable shard
  holds KV/tp heads (the per-chip cache footprint divides by tp);
- ``decode_step_compiles == warmup_compiles`` at the end of every cell
  (occupancy changes, table growth, and CoW copies never recompile at
  tp>1, same pin as tp=1);
- a supervised engine (EngineSupervisor) crashed mid-decode by the
  seeded fault injector rebuilds, RECONSTRUCTS the mesh through the
  factory, and replays the in-flight request bit-identically;
- the pallas paged-attention kernel (``kv_attend="pallas"``, ISSUE 18)
  holds all of the above under shard_map — including the cache
  leaf-set regression proving the kernel adds no scratch leaves for
  serve/sharding.py's rebuild rules to miss.

Driven by tests/test_serve_tp.py (slow-marked: multi-device needs its
own process) and tools/serve_smoke.py; run standalone:

    python tools/serve_tp_check.py            # tp=2 host devices
    python tools/serve_tp_check.py --tp 4
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _force_host_devices(n: int) -> None:
    """Set the host-device flag BEFORE any jax import (it only affects
    the CPU platform — on real hardware the mesh uses the chips). A
    smaller pre-pinned count is RAISED, not respected: callers like
    bench.py's smoke mode pin 1 for their own sections."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m and int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}"
        )
    elif not m:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip()
        )


def run_matrix(tp: int) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules

    if len(jax.devices()) < tp:
        print(f"serve_tp_check: need {tp} devices, have "
              f"{len(jax.devices())}", file=sys.stderr)
        return 1
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(mesh, params, param_sharding_rules())

    def solo(prompt, steps, *, temperature=0.0, seed=0):
        kw = {}
        if temperature > 0:
            kw = dict(temperature=temperature,
                      rng=jax.random.PRNGKey(seed))
        return np.asarray(
            generate(cfg, sharded, jnp.asarray(prompt), steps, **kw)
        )[0]

    from tf_operator_tpu.serve.engine import ContinuousEngine

    rng = np.random.default_rng(7)
    failures = 0
    for kv_paged in (False, True):
        for chunk in (None, 4):
            label = (f"{'paged' if kv_paged else 'dense'}/"
                     f"{'chunked' if chunk else 'oneshot'}")
            eng = ContinuousEngine(
                cfg, params, max_slots=3, kv_paged=kv_paged,
                kv_block=8, prefill_chunk=chunk, mesh=mesh,
            )
            # The storage is REALLY sharded: this device's shard holds
            # KV/tp heads (KV=2 here, so 1 head per device at tp=2).
            def kv_leaf(t):
                from collections.abc import Mapping

                for k, v in t.items():
                    if isinstance(v, Mapping):
                        found = kv_leaf(v)
                        if found is not None:
                            return found
                    elif k in ("pool_key", "cached_key"):
                        return v
                return None

            leaf = kv_leaf(eng._cache)
            local_kv = leaf.addressable_shards[0].data.shape[-2]
            assert local_kv == cfg.kv_heads // tp, (
                f"{label}: per-device shard holds {local_kv} KV heads, "
                f"expected {cfg.kv_heads // tp}"
            )

            # Occupancy walk: joins/retires mid-decode, slot reuse, a
            # sampled slot, and (paged) an exact shared-prefix re-join.
            p1 = rng.integers(0, 64, (1, 9)).astype(np.int32)
            p2 = rng.integers(0, 64, (1, 5)).astype(np.int32)
            plan = {"a": (p1, 10, 0.0, 0), "b": (p2, 6, 0.0, 0),
                    "c": (p1, 8, 0.9, 3), "d": (p2, 4, 0.0, 0)}
            joins = {2: "b", 4: "c", 12: "d"}  # step index -> join
            live, outs = {}, {}
            live[eng.join(jnp.asarray(p1), num_steps=10)] = ("a", 10, [])
            i = 0
            while live:
                toks = eng.step()
                i += 1
                for s in list(live):
                    name, n, acc = live[s]
                    acc.append(int(toks[s]))
                    if len(acc) == n:
                        eng.retire(s)
                        outs[name] = acc
                        del live[s]
                if i in joins:
                    name = joins[i]
                    p, n, t, seed = plan[name]
                    s = eng.join(jnp.asarray(p), num_steps=n,
                                 temperature=t, seed=seed)
                    assert s is not None, f"{label}: no slot for {name}"
                    live[s] = (name, n, [])
            for name, (p, n, t, seed) in plan.items():
                want = solo(p, n, temperature=t, seed=seed)
                if not np.array_equal(np.asarray(outs[name]), want):
                    print(f"serve_tp_check: {label} request {name} "
                          f"DIVERGED from solo generate", file=sys.stderr)
                    failures += 1
            if eng.decode_step_compiles != eng.warmup_compiles:
                print(f"serve_tp_check: {label} recompiled "
                      f"({eng.decode_step_compiles} != warmup "
                      f"{eng.warmup_compiles})", file=sys.stderr)
                failures += 1
            saved = getattr(eng, "prefill_tokens_saved", 0)
            if kv_paged and saved < p1.shape[1]:
                print(f"serve_tp_check: {label} shared-prefix admission "
                      f"saved only {saved} tokens", file=sys.stderr)
                failures += 1
            print(f"serve_tp_check: {label} ok "
                  f"(kv/device {local_kv}, compiles "
                  f"{eng.decode_step_compiles}=warmup, saved {saved})",
                  flush=True)
    return failures


def run_spec(tp: int) -> int:
    """Batch-wide speculative decode at tp>1 (ISSUE 15): the spec
    engine on the mesh — draft params sharded by the same rules, kv8
    scale sidecars riding the head shard — bit-identical per slot to
    solo ``speculative_generate`` with the SAME tp-sharded params
    (greedy AND sampled), across a join/retire walk, in both KV
    layouts plus the paged-kv8 cell, with compiles == warmup."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.spec_decode import speculative_generate
    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules
    from tf_operator_tpu.serve.engine import ContinuousEngine

    K = 2
    base = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=2,
                d_ff=64, max_seq_len=64, dtype=jnp.float32)
    cfg = TransformerConfig(**base)
    dcfg = TransformerConfig(**{**base, "n_layers": 1})
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    dparams = Transformer(dcfg).init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(mesh, params, param_sharding_rules())
    dsharded = shard_params_by_rules(mesh, dparams,
                                     param_sharding_rules())

    rng = np.random.default_rng(13)
    p1 = rng.integers(0, 64, (1, 9)).astype(np.int32)
    p2 = rng.integers(0, 64, (1, 5)).astype(np.int32)
    failures = 0
    from dataclasses import replace

    cells = [("spec/dense", cfg, dcfg, dict(kv_paged=False)),
             ("spec/paged", cfg, dcfg, dict(kv_paged=True)),
             ("spec/paged-kv8", replace(cfg, kv_int8=True),
              replace(dcfg, kv_int8=True), dict(kv_paged=True))]
    for label, tcfg, tdcfg, kw in cells:
        eng = ContinuousEngine(
            tcfg, params, max_slots=3, kv_block=8, mesh=mesh,
            spec_k=K, draft_cfg=tdcfg, draft_params=dparams, **kw,
        )

        def solo_spec(prompt, steps, temperature=0.0, seed=0):
            skw = {}
            if temperature > 0:
                skw = dict(temperature=temperature,
                           rng=jax.random.PRNGKey(seed))
            out, _ = speculative_generate(
                tcfg, sharded, tdcfg, dsharded, jnp.asarray(prompt),
                steps, k=K, **skw,
            )
            return np.asarray(out)[0]

        plan = {"a": (p1, 10, 0.0, 0), "b": (p2, 6, 0.9, 11)}
        sa = eng.join(jnp.asarray(p1), num_steps=10)
        state = {sa: ("a", 10, [])}
        toks, counts = eng.spec_step()
        for j in range(int(counts[sa])):
            state[sa][2].append(int(toks[sa, j]))
        sb = eng.join(jnp.asarray(p2), num_steps=6, temperature=0.9,
                      seed=11)
        state[sb] = ("b", 6, [])
        done: dict = {}
        for _ in range(40):
            if not state:
                break
            toks, counts = eng.spec_step()
            for s in list(state):
                name, n, acc = state[s]
                for j in range(int(counts[s])):
                    if len(acc) < n:
                        acc.append(int(toks[s, j]))
                if len(acc) >= n:
                    eng.retire(s)
                    done[name] = acc
                    del state[s]
        for name, (p, n, t, seed) in plan.items():
            want = solo_spec(p, n, t, seed)[:n]
            if not np.array_equal(np.asarray(done[name]), want):
                print(f"serve_tp_check: {label} request {name} DIVERGED "
                      f"from solo speculative_generate", file=sys.stderr)
                failures += 1
        if eng.decode_step_compiles != eng.warmup_compiles:
            print(f"serve_tp_check: {label} recompiled "
                  f"({eng.decode_step_compiles} != warmup "
                  f"{eng.warmup_compiles})", file=sys.stderr)
            failures += 1
        print(f"serve_tp_check: {label} ok (k={K}, compiles "
              f"{eng.decode_step_compiles}=warmup, accept_rate "
              f"{eng.spec_debug()['accept_rate']})", flush=True)
    return failures


def run_constrain(tp: int) -> int:
    """Constrained decoding at tp>1 (ISSUE 19): the paged engine on the
    mesh with a grammar-constrained lane co-resident with a free
    sampled lane — the constraint pool's allow/next tables and the
    per-slot FSM vector are REPLICATED (sharding.replicate_put: the
    mask gather reads full vocab rows on every shard, and vocab is
    unsharded), so the constrained lane must be bit-identical to solo
    ``constrained_generate`` with the SAME tp-sharded params, the free
    lane to plain ``generate``, with compiles == warmup."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules
    from tf_operator_tpu.serve.constrain import (
        ConstraintCompiler,
        constrained_generate,
        default_vocab,
    )
    from tf_operator_tpu.serve.engine import ContinuousEngine

    # V=128: the chr-identity vocab must cover ASCII for the grammar.
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(mesh, params, param_sharding_rules())
    comp = ConstraintCompiler(default_vocab(cfg.vocab_size))
    prog = comp.compile({"regex": "[0-9]{2,6}"})

    rng = np.random.default_rng(17)
    p_con = rng.integers(0, 128, (1, 6)).astype(np.int32)
    p_free = rng.integers(0, 128, (1, 9)).astype(np.int32)
    failures = 0
    eng = ContinuousEngine(
        cfg, params, max_slots=2, kv_paged=True, kv_block=8, mesh=mesh,
        constrain_rows=16,
    )
    s_con = eng.join(jnp.asarray(p_con), num_steps=10, program=prog)
    s_free = eng.join(jnp.asarray(p_free), num_steps=10,
                      temperature=0.9, seed=3)
    got = {s_con: [], s_free: []}
    for _ in range(10):
        toks = eng.step()
        for s in got:
            got[s].append(int(toks[s]))
    eng.retire(s_con)
    eng.retire(s_free)
    want_con = np.asarray(constrained_generate(
        cfg, sharded, jnp.asarray(p_con), 10, program=prog
    ))[0]
    want_free = np.asarray(generate(
        cfg, sharded, jnp.asarray(p_free), 10, temperature=0.9,
        rng=jax.random.PRNGKey(3),
    ))[0]
    if not np.array_equal(np.asarray(got[s_con]), want_con):
        print("serve_tp_check: constrain lane DIVERGED from solo "
              "constrained_generate", file=sys.stderr)
        failures += 1
    if not np.array_equal(np.asarray(got[s_free]), want_free):
        print("serve_tp_check: free lane beside the constrained one "
              "DIVERGED from solo generate", file=sys.stderr)
        failures += 1
    if eng.decode_step_compiles != eng.warmup_compiles:
        print(f"serve_tp_check: constrain cell recompiled "
              f"({eng.decode_step_compiles} != warmup "
              f"{eng.warmup_compiles})", file=sys.stderr)
        failures += 1
    print(f"serve_tp_check: constrain/paged ok (compiles "
          f"{eng.decode_step_compiles}=warmup, "
          f"{eng.constrain_debug()['rows_used']} pool rows)",
          flush=True)
    return failures


def run_pallas(tp: int) -> int:
    """Paged-attention kernel at tp>1 (ISSUE 18): the pallas attend
    runs under shard_map over the tp axis (a pallas call has no SPMD
    partitioning rule) with the pool head-sharded and ZERO collectives
    inside the attend. Proves, for {f32, kv8} x pallas:

    - engine output bit-identical to solo ``generate`` with the SAME
      tp-sharded params, across a join/retire occupancy walk with a
      sampled slot;
    - the cache leaf SET (paths, shapes, dtypes) is identical to the
      gather engine's — the kernel's scratch is pallas-internal, so
      serve/sharding.py's supervisor-rebuild reconstruction needs no
      new rules (the regression this guards);
    - the KV pool is really head-sharded (KV/tp per device) and
      ``decode_step_compiles == warmup_compiles`` at the end;
    - a supervised pallas engine crashed mid-decode rebuilds through
      the factory and replays bit-identically without a second
      compile."""
    from dataclasses import replace

    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.faultinject import FaultInjector
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(mesh, params, param_sharding_rules())

    def leafset(tree):
        return {
            (jax.tree_util.keystr(path), leaf.shape, str(leaf.dtype))
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                tree
            )[0]
        }

    rng = np.random.default_rng(21)
    p1 = rng.integers(0, 64, (1, 9)).astype(np.int32)
    p2 = rng.integers(0, 64, (1, 5)).astype(np.int32)
    failures = 0
    for label, tcfg in (("pallas/f32", cfg),
                        ("pallas/kv8", replace(cfg, kv_int8=True))):
        eng = ContinuousEngine(
            tcfg, params, max_slots=3, kv_paged=True, kv_block=8,
            mesh=mesh, kv_attend="pallas",
        )
        gather = ContinuousEngine(
            tcfg, params, max_slots=3, kv_paged=True, kv_block=8,
            mesh=mesh,
        )
        if leafset(eng._cache) != leafset(gather._cache):
            print(f"serve_tp_check: {label} cache leaf set differs "
                  f"from the gather engine's — sharding.py's rebuild "
                  f"rules no longer cover it", file=sys.stderr)
            failures += 1
        del gather
        kv_pool = [
            leaf for path, leaf
            in jax.tree_util.tree_flatten_with_path(eng._cache)[0]
            if "pool_key" in jax.tree_util.keystr(path)
        ][0]
        local_kv = kv_pool.addressable_shards[0].data.shape[-2]
        if local_kv != cfg.kv_heads // tp:
            print(f"serve_tp_check: {label} per-device pool shard "
                  f"holds {local_kv} KV heads, expected "
                  f"{cfg.kv_heads // tp}", file=sys.stderr)
            failures += 1

        def solo(prompt, steps, *, temperature=0.0, seed=0):
            kw = {}
            if temperature > 0:
                kw = dict(temperature=temperature,
                          rng=jax.random.PRNGKey(seed))
            return np.asarray(
                generate(tcfg, sharded, jnp.asarray(prompt), steps,
                         **kw)
            )[0]

        plan = {"a": (p1, 10, 0.0, 0), "b": (p2, 6, 0.0, 0),
                "c": (p1, 8, 0.9, 3)}
        joins = {2: "b", 5: "c"}
        live, outs = {}, {}
        live[eng.join(jnp.asarray(p1), num_steps=10)] = ("a", 10, [])
        i = 0
        while live:
            toks = eng.step()
            i += 1
            for s in list(live):
                name, n, acc = live[s]
                acc.append(int(toks[s]))
                if len(acc) == n:
                    eng.retire(s)
                    outs[name] = acc
                    del live[s]
            if i in joins:
                name = joins[i]
                p, n, t, seed = plan[name]
                s = eng.join(jnp.asarray(p), num_steps=n,
                             temperature=t, seed=seed)
                assert s is not None, f"{label}: no slot for {name}"
                live[s] = (name, n, [])
        for name, (p, n, t, seed) in plan.items():
            want = solo(p, n, temperature=t, seed=seed)
            if not np.array_equal(np.asarray(outs[name]), want):
                print(f"serve_tp_check: {label} request {name} "
                      f"DIVERGED from solo generate", file=sys.stderr)
                failures += 1
        if eng.decode_step_compiles != eng.warmup_compiles:
            print(f"serve_tp_check: {label} recompiled "
                  f"({eng.decode_step_compiles} != warmup "
                  f"{eng.warmup_compiles})", file=sys.stderr)
            failures += 1
        print(f"serve_tp_check: {label} ok (kv/device {local_kv}, "
              f"leaf set == gather, compiles "
              f"{eng.decode_step_compiles}=warmup)", flush=True)

    # Supervisor rebuild with the kernel in the loop: the rebuilt
    # engine's cache reconstructs through the SAME sharding.py rules
    # (no kernel-side leaves to miss) and replays without recompiling.
    inj = FaultInjector(seed=3)
    sup = EngineSupervisor(
        lambda: ContinuousEngine(cfg, params, max_slots=2, kv_block=8,
                                 kv_paged=True, mesh=mesh,
                                 kv_attend="pallas", faults=inj),
        resilience=ResilienceConfig(watchdog_stall_s=10.0,
                                    restart_backoff_s=0.05,
                                    max_restarts=3),
        faults=inj,
    )
    try:
        prompt = np.random.default_rng(17).integers(
            0, cfg.vocab_size, (1, 11)
        ).astype(np.int32)
        want = np.asarray(
            generate(cfg, sharded, jnp.asarray(prompt), 20)
        )
        inj.arm(f"step_raise@{inj.invocations['step_raise'] + 5}")
        out = sup.submit(prompt, 20, timeout=180)
        if sup.restarts != 1:
            print(f"serve_tp_check: pallas replay expected 1 restart, "
                  f"got {sup.restarts}", file=sys.stderr)
            failures += 1
        if not np.array_equal(out, want):
            print("serve_tp_check: pallas post-crash replay != solo",
                  file=sys.stderr)
            failures += 1
        if sup.engine.decode_step_compiles != \
                sup.engine.warmup_compiles:
            print("serve_tp_check: rebuilt pallas engine recompiled",
                  file=sys.stderr)
            failures += 1
        if not failures:
            print(f"serve_tp_check: pallas supervisor replay ok "
                  f"(1 restart, replay bit-identical)", flush=True)
    finally:
        sup.stop(timeout=30.0)
    return failures


def run_supervisor_replay(tp: int) -> int:
    """Crash a supervised tp engine mid-decode: the rebuild reconstructs
    the mesh (same factory, same shardings) and the replay is
    bit-identical."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.faultinject import FaultInjector
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(mesh, params, param_sharding_rules())
    inj = FaultInjector(seed=1)
    sup = EngineSupervisor(
        lambda: ContinuousEngine(cfg, params, max_slots=2, kv_block=8,
                                 mesh=mesh, faults=inj),
        resilience=ResilienceConfig(watchdog_stall_s=10.0,
                                    restart_backoff_s=0.05,
                                    max_restarts=3),
        faults=inj,
    )
    try:
        prompt = np.random.default_rng(9).integers(
            0, cfg.vocab_size, (1, 11)
        ).astype(np.int32)
        want = np.asarray(
            generate(cfg, sharded, jnp.asarray(prompt), 24)
        )
        if not np.array_equal(sup.submit(prompt, 24), want):
            print("serve_tp_check: pre-crash output != solo",
                  file=sys.stderr)
            return 1
        inj.arm(f"step_raise@{inj.invocations['step_raise'] + 6}")
        out = sup.submit(prompt, 24, timeout=180)
        if sup.restarts != 1:
            print(f"serve_tp_check: expected 1 restart, got "
                  f"{sup.restarts}", file=sys.stderr)
            return 1
        if not np.array_equal(out, want):
            print("serve_tp_check: post-crash replay != solo",
                  file=sys.stderr)
            return 1
        if sup.engine.decode_step_compiles != \
                sup.engine.warmup_compiles:
            print("serve_tp_check: rebuilt engine recompiled",
                  file=sys.stderr)
            return 1
        if sup.mesh_devices != tp:
            print(f"serve_tp_check: rebuilt mesh width "
                  f"{sup.mesh_devices} != {tp}", file=sys.stderr)
            return 1
        print(f"serve_tp_check: supervisor replay ok (1 restart, "
              f"mesh reconstructed at {tp} devices, replay "
              f"bit-identical)", flush=True)
        return 0
    finally:
        sup.stop(timeout=30.0)


def run_tpdp(tp: int, dp: int) -> int:
    """Pod-scale decode (ISSUE 20): ONE ContinuousEngine over a 2-D
    {tp}x{dp} mesh — slot-leading state and the pool's block axis shard
    over dp, K/V heads and params over tp, ONE compiled step drives the
    whole slice. Proves, per cell:

    - greedy AND sampled output bit-identical to solo ``generate`` with
      the same tp-sharded params across an occupancy walk that crosses
      BOTH axes (joins/retires/slot reuse on every dp shard), for
      {dense, paged, kv8, pallas};
    - the storage is REALLY 2-D sharded: each device holds
      blocks/dp x KV/tp of the pool (dense: slots/dp rows);
    - every paged slot's table references only its OWN dp shard's block
      extent (the dp_pool legality invariant);
    - ``decode_step_compiles == warmup_compiles`` at the end of every
      cell (the zero-recompile pin holds on the 2-D mesh);
    - shipped-KV ingest and host-tier restore land on the dp shard that
      SEATS the request (the shard with free slots), and the admission
      plan exact-hits the landed prefix there — decode bit-identical;
    - a supervised tp×dp engine crashed mid-decode rebuilds,
      reconstructs the 2-D mesh through the factory, and replays
      bit-identically."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.sharding import shard_of_slot

    need = tp * dp
    if len(jax.devices()) < need:
        print(f"serve_tp_check: need {need} devices, have "
              f"{len(jax.devices())}", file=sys.stderr)
        return 1
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    cfg8 = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, kv_int8=True,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp, "dp": dp}, jax.devices()[:need])
    # The oracle runs on the CANONICAL tp-only mesh (the exact solo
    # baseline run_matrix pins): the claim under test is that adding
    # the dp axis changes NOTHING bitwise vs that baseline. (Running
    # solo generate itself on the wider mesh lets GSPMD pick different
    # layouts for the unconstrained b=1 activations — ULP drift that
    # can flip a sampled categorical draw; the engine does not drift.)
    omesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(omesh, params,
                                    param_sharding_rules())

    def solo(prompt, steps, *, c=cfg, temperature=0.0, seed=0):
        kw = {}
        if temperature > 0:
            kw = dict(temperature=temperature,
                      rng=jax.random.PRNGKey(seed))
        return np.asarray(
            generate(c, sharded, jnp.asarray(prompt), steps, **kw)
        )[0]

    def first_leaf(tree, names):
        from collections.abc import Mapping

        for k, v in tree.items():
            if isinstance(v, Mapping):
                found = first_leaf(v, names)
                if found is not None:
                    return found
            elif k in names:
                return v
        return None

    def extent_violations(eng, label):
        """Every live paged slot's blocks inside its OWN shard's
        extent — the invariant that makes the dp-sharded pool legal."""
        for s, st in eng._slot_state.items():
            lo, hi = eng.blocks.shard_extent(
                shard_of_slot(s, eng.max_slots, dp)
            )
            bad = [b for b in st["private"] + st["shared"]
                   if b and not lo <= b < hi]
            if bad:
                print(f"serve_tp_check: tpdp {label} slot {s} holds "
                      f"blocks {bad} outside its dp shard extent "
                      f"[{lo}, {hi})", file=sys.stderr)
                return 1
        return 0

    failures = 0
    rng = np.random.default_rng(11)
    slots = 2 * dp  # two slots per dp shard
    cells = [
        ("dense", cfg, dict(kv_paged=False)),
        ("paged", cfg, dict(kv_paged=True)),
        ("kv8", cfg8, dict(kv_paged=True)),
        ("pallas", cfg, dict(kv_paged=True, kv_attend="pallas")),
    ]
    for label, c, kw in cells:
        eng = ContinuousEngine(c, params, max_slots=slots, kv_block=8,
                               mesh=mesh, **kw)
        # The storage is REALLY 2-D sharded: block axis (dense: slot
        # axis) divided by dp, KV heads by tp, on every device.
        leaf = first_leaf(eng._cache, ("pool_key", "cached_key"))
        local = leaf.addressable_shards[0].data.shape
        want0 = (eng.kv_blocks // dp) if eng.kv_paged else slots // dp
        if local[0] != want0 or local[-2] != c.kv_heads // tp:
            print(f"serve_tp_check: tpdp {label} per-device shard "
                  f"{local} is not blocks/dp x KV/tp", file=sys.stderr)
            failures += 1
        # Occupancy walk crossing BOTH axes: joins/retires mid-decode,
        # slot reuse past one shard's slice, a sampled lane, and an
        # exact shared-prefix re-join ("d" repeats p1's prompt).
        p1 = rng.integers(0, 64, (1, 9)).astype(np.int32)
        p2 = rng.integers(0, 64, (1, 5)).astype(np.int32)
        p3 = rng.integers(0, 64, (1, 12)).astype(np.int32)
        plan = {"a": (p1, 10, 0.0, 0), "b": (p2, 6, 0.0, 0),
                "c": (p3, 8, 0.9, 3), "d": (p1, 8, 0.0, 0),
                "e": (p2, 4, 0.0, 0)}
        joins = {1: "b", 2: "c", 4: "d", 12: "e"}
        live, outs, shards_used = {}, {}, set()
        s0 = eng.join(jnp.asarray(p1), num_steps=10)
        live[s0] = ("a", 10, [])
        shards_used.add(shard_of_slot(s0, slots, dp))
        i = 0
        while live:
            toks = eng.step()
            i += 1
            for s in list(live):
                name, n, acc = live[s]
                acc.append(int(toks[s]))
                if len(acc) == n:
                    eng.retire(s)
                    outs[name] = acc
                    del live[s]
            if i in joins:
                name = joins[i]
                p, n, t, seed = plan[name]
                s = eng.join(jnp.asarray(p), num_steps=n,
                             temperature=t, seed=seed)
                assert s is not None, f"tpdp {label}: no slot for {name}"
                live[s] = (name, n, [])
                shards_used.add(shard_of_slot(s, slots, dp))
                if eng.kv_paged:
                    failures += extent_violations(eng, label)
        for name, (p, n, t, seed) in plan.items():
            want = solo(p, n, c=c, temperature=t, seed=seed)
            if not np.array_equal(np.asarray(outs[name]), want):
                print(f"serve_tp_check: tpdp {label} request {name} "
                      f"DIVERGED from solo generate", file=sys.stderr)
                failures += 1
        if len(shards_used) < dp:
            print(f"serve_tp_check: tpdp {label} walk never left dp "
                  f"shard(s) {shards_used}", file=sys.stderr)
            failures += 1
        if eng.decode_step_compiles != eng.warmup_compiles:
            print(f"serve_tp_check: tpdp {label} recompiled "
                  f"({eng.decode_step_compiles} != warmup "
                  f"{eng.warmup_compiles})", file=sys.stderr)
            failures += 1
        print(f"serve_tp_check: tpdp {label} ok (blocks-or-slots/dev "
              f"{local[0]}, kv/dev {local[-2]}, shards {sorted(shards_used)}, "
              f"compiles {eng.decode_step_compiles}=warmup)", flush=True)

    # dp-shard KV ingest: fill slots until ONE shard has the only free
    # seats, then ship a prefilled prompt in — the ingest must land the
    # blocks on THAT shard's extent, and the admission plan must
    # exact-hit them there (prefill skipped, decode bit-identical).
    from tf_operator_tpu.serve.disagg import PrefillWorker, decode_shipment

    for source in ("ship", "tier"):
        eng = ContinuousEngine(cfg, params, max_slots=slots, kv_block=8,
                               mesh=mesh)
        prompt = rng.integers(0, 64, (1, 9)).astype(np.int32)
        if source == "tier":
            from tf_operator_tpu.serve.tier import HostTier

            eng.host_tier = HostTier(1 << 22)
            # Decode the prompt once and retire: the freed exact prefix
            # entry SPILLS into the host tier on the way out.
            s = eng.join(jnp.asarray(prompt), num_steps=3)
            for _ in range(3):
                eng.step()
            eng.retire(s)
        while sum(1 for i in range(dp) if eng.alloc.free_in(i)) > 1:
            s = eng.join(jnp.asarray(
                rng.integers(0, 64, (1, 5)).astype(np.int32)
            ), num_steps=20)
            assert s is not None
        target = next(i for i in range(dp) if eng.alloc.free_in(i))
        lo, hi = eng.blocks.shard_extent(target)
        if source == "ship":
            pw = PrefillWorker(cfg, params, kv_block=8)
            shp = decode_shipment(pw.prefill(prompt))
            hold = eng.ingest_shipment(shp, reserve_steps=4)
            ok = hold is not None and hold.blocks
        else:
            hold, outcome = eng.restore_from_tier(prompt,
                                                  reserve_steps=4)
            ok = outcome == "ok" and hold.blocks
        if not ok:
            print(f"serve_tp_check: tpdp {source} ingest did not land",
                  file=sys.stderr)
            failures += 1
            continue
        bad = [b for b in hold.blocks if not lo <= b < hi]
        if bad:
            print(f"serve_tp_check: tpdp {source} ingest blocks {bad} "
                  f"outside seating shard {target}'s extent [{lo}, {hi})",
                  file=sys.stderr)
            failures += 1
        adm = eng.plan_admission(prompt, 4)
        if adm is None or adm.dp_shard != target or adm.prefill_tokens:
            print(f"serve_tp_check: tpdp {source} plan did not "
                  f"exact-hit the landed prefix on shard {target} "
                  f"(plan={adm and (adm.dp_shard, adm.prefill_tokens)})",
                  file=sys.stderr)
            failures += 1
            eng.release_plan(adm)
            eng.release_shipment(hold)
            continue
        s = eng.join_planned(adm)
        eng.release_shipment(hold)
        out = [int(eng.step()[s]) for _ in range(4)]
        if not np.array_equal(out, solo(prompt, 4)):
            print(f"serve_tp_check: tpdp {source}-landed decode "
                  f"DIVERGED from solo generate", file=sys.stderr)
            failures += 1
        if eng.decode_step_compiles != eng.warmup_compiles:
            print(f"serve_tp_check: tpdp {source} ingest recompiled",
                  file=sys.stderr)
            failures += 1
        print(f"serve_tp_check: tpdp {source} ingest ok (landed on "
              f"shard {target} extent [{lo}, {hi}), exact-hit, "
              f"bit-identical)", flush=True)

    # Crash -> rebuild -> replay on the 2-D mesh: the factory
    # reconstructs tp x dp and the replay is bit-identical.
    from tf_operator_tpu.serve.faultinject import FaultInjector
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
    )

    inj = FaultInjector(seed=1)
    sup = EngineSupervisor(
        lambda: ContinuousEngine(cfg, params, max_slots=slots,
                                 kv_block=8, mesh=mesh, faults=inj),
        resilience=ResilienceConfig(watchdog_stall_s=10.0,
                                    restart_backoff_s=0.05,
                                    max_restarts=3),
        faults=inj,
    )
    try:
        prompt = rng.integers(0, 64, (1, 11)).astype(np.int32)
        want = solo(prompt, 24)
        if not np.array_equal(sup.submit(prompt, 24)[0], want):
            print("serve_tp_check: tpdp pre-crash output != solo",
                  file=sys.stderr)
            failures += 1
        inj.arm(f"step_raise@{inj.invocations['step_raise'] + 6}")
        out = sup.submit(prompt, 24, timeout=180)
        if sup.restarts != 1 or not np.array_equal(out[0], want):
            print("serve_tp_check: tpdp post-crash replay diverged or "
                  f"restarts={sup.restarts}", file=sys.stderr)
            failures += 1
        if sup.mesh_devices != need:
            print(f"serve_tp_check: tpdp rebuilt mesh width "
                  f"{sup.mesh_devices} != {need}", file=sys.stderr)
            failures += 1
        print(f"serve_tp_check: tpdp supervisor replay ok (1 restart, "
              f"2-D mesh reconstructed at {need} devices, replay "
              f"bit-identical)", flush=True)
    finally:
        sup.stop(timeout=30.0)
    return failures


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tp", type=int, default=2,
                   help="mesh width (forced as CPU host devices when "
                        "the platform is CPU)")
    p.add_argument("--dp", type=int, default=1,
                   help="batch-parallel mesh axis over slots; > 1 runs "
                        "the pod-scale tp x dp cells INSTEAD of the "
                        "tp-only pass (tp*dp host devices)")
    p.add_argument("--skip-supervisor", action="store_true",
                   help="matrix only (the replay drill builds 2+ more "
                        "engines)")
    args = p.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _force_host_devices(args.tp * max(1, args.dp))
    if args.dp > 1:
        failures = run_tpdp(args.tp, args.dp)
        if failures:
            print(f"serve_tp_check: FAIL ({failures} failure(s))",
                  file=sys.stderr)
            return 1
        print(f"serve_tp_check: OK (tp={args.tp}, dp={args.dp}, tpdp "
              f"matrix + ingest + supervisor replay bit-identical, "
              f"zero post-warmup recompiles)", flush=True)
        return 0
    failures = run_matrix(args.tp)
    failures += run_spec(args.tp)
    failures += run_constrain(args.tp)
    failures += run_pallas(args.tp)
    if not args.skip_supervisor:
        failures += run_supervisor_replay(args.tp)
    if failures:
        print(f"serve_tp_check: FAIL ({failures} failure(s))",
              file=sys.stderr)
        return 1
    print(f"serve_tp_check: OK (tp={args.tp}, matrix + spec "
          f"+ constrain + pallas + supervisor replay bit-identical, "
          f"zero post-warmup recompiles)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
