#!/usr/bin/env python
"""SPMD tensor-parallel decode exactness check: the continuous engine on
a >1-device mesh, bit-identical to solo generate, with zero decode
recompiles — the multi-chip half of the PR-5 exactness matrix, runnable
anywhere via the XLA host-device trick.

Proves, at ``--tp`` devices (default 2, forced as CPU host devices
BEFORE jax imports so the check needs no hardware):

- engine greedy output == solo ``generate`` with the SAME tp-sharded
  params, bit-for-bit, for every cell of {dense, paged} x {one-shot,
  chunked prefill}, across join/retire mid-decode, slot reuse, sampled
  (temperature + seeded rng) slots, and — paged — shared-prefix
  admission;
- the KV storage is REALLY sharded: each device's addressable shard
  holds KV/tp heads (the per-chip cache footprint divides by tp);
- ``decode_step_compiles == warmup_compiles`` at the end of every cell
  (occupancy changes, table growth, and CoW copies never recompile at
  tp>1, same pin as tp=1);
- a supervised engine (EngineSupervisor) crashed mid-decode by the
  seeded fault injector rebuilds, RECONSTRUCTS the mesh through the
  factory, and replays the in-flight request bit-identically;
- the pallas paged-attention kernel (``kv_attend="pallas"``, ISSUE 18)
  holds all of the above under shard_map — including the cache
  leaf-set regression proving the kernel adds no scratch leaves for
  serve/sharding.py's rebuild rules to miss.

Driven by tests/test_serve_tp.py (slow-marked: multi-device needs its
own process) and tools/serve_smoke.py; run standalone:

    python tools/serve_tp_check.py            # tp=2 host devices
    python tools/serve_tp_check.py --tp 4
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _force_host_devices(n: int) -> None:
    """Set the host-device flag BEFORE any jax import (it only affects
    the CPU platform — on real hardware the mesh uses the chips). A
    smaller pre-pinned count is RAISED, not respected: callers like
    bench.py's smoke mode pin 1 for their own sections."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m and int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}"
        )
    elif not m:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip()
        )


def run_matrix(tp: int) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules

    if len(jax.devices()) < tp:
        print(f"serve_tp_check: need {tp} devices, have "
              f"{len(jax.devices())}", file=sys.stderr)
        return 1
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(mesh, params, param_sharding_rules())

    def solo(prompt, steps, *, temperature=0.0, seed=0):
        kw = {}
        if temperature > 0:
            kw = dict(temperature=temperature,
                      rng=jax.random.PRNGKey(seed))
        return np.asarray(
            generate(cfg, sharded, jnp.asarray(prompt), steps, **kw)
        )[0]

    from tf_operator_tpu.serve.engine import ContinuousEngine

    rng = np.random.default_rng(7)
    failures = 0
    for kv_paged in (False, True):
        for chunk in (None, 4):
            label = (f"{'paged' if kv_paged else 'dense'}/"
                     f"{'chunked' if chunk else 'oneshot'}")
            eng = ContinuousEngine(
                cfg, params, max_slots=3, kv_paged=kv_paged,
                kv_block=8, prefill_chunk=chunk, mesh=mesh,
            )
            # The storage is REALLY sharded: this device's shard holds
            # KV/tp heads (KV=2 here, so 1 head per device at tp=2).
            def kv_leaf(t):
                from collections.abc import Mapping

                for k, v in t.items():
                    if isinstance(v, Mapping):
                        found = kv_leaf(v)
                        if found is not None:
                            return found
                    elif k in ("pool_key", "cached_key"):
                        return v
                return None

            leaf = kv_leaf(eng._cache)
            local_kv = leaf.addressable_shards[0].data.shape[-2]
            assert local_kv == cfg.kv_heads // tp, (
                f"{label}: per-device shard holds {local_kv} KV heads, "
                f"expected {cfg.kv_heads // tp}"
            )

            # Occupancy walk: joins/retires mid-decode, slot reuse, a
            # sampled slot, and (paged) an exact shared-prefix re-join.
            p1 = rng.integers(0, 64, (1, 9)).astype(np.int32)
            p2 = rng.integers(0, 64, (1, 5)).astype(np.int32)
            plan = {"a": (p1, 10, 0.0, 0), "b": (p2, 6, 0.0, 0),
                    "c": (p1, 8, 0.9, 3), "d": (p2, 4, 0.0, 0)}
            joins = {2: "b", 4: "c", 12: "d"}  # step index -> join
            live, outs = {}, {}
            live[eng.join(jnp.asarray(p1), num_steps=10)] = ("a", 10, [])
            i = 0
            while live:
                toks = eng.step()
                i += 1
                for s in list(live):
                    name, n, acc = live[s]
                    acc.append(int(toks[s]))
                    if len(acc) == n:
                        eng.retire(s)
                        outs[name] = acc
                        del live[s]
                if i in joins:
                    name = joins[i]
                    p, n, t, seed = plan[name]
                    s = eng.join(jnp.asarray(p), num_steps=n,
                                 temperature=t, seed=seed)
                    assert s is not None, f"{label}: no slot for {name}"
                    live[s] = (name, n, [])
            for name, (p, n, t, seed) in plan.items():
                want = solo(p, n, temperature=t, seed=seed)
                if not np.array_equal(np.asarray(outs[name]), want):
                    print(f"serve_tp_check: {label} request {name} "
                          f"DIVERGED from solo generate", file=sys.stderr)
                    failures += 1
            if eng.decode_step_compiles != eng.warmup_compiles:
                print(f"serve_tp_check: {label} recompiled "
                      f"({eng.decode_step_compiles} != warmup "
                      f"{eng.warmup_compiles})", file=sys.stderr)
                failures += 1
            saved = getattr(eng, "prefill_tokens_saved", 0)
            if kv_paged and saved < p1.shape[1]:
                print(f"serve_tp_check: {label} shared-prefix admission "
                      f"saved only {saved} tokens", file=sys.stderr)
                failures += 1
            print(f"serve_tp_check: {label} ok "
                  f"(kv/device {local_kv}, compiles "
                  f"{eng.decode_step_compiles}=warmup, saved {saved})",
                  flush=True)
    return failures


def run_spec(tp: int) -> int:
    """Batch-wide speculative decode at tp>1 (ISSUE 15): the spec
    engine on the mesh — draft params sharded by the same rules, kv8
    scale sidecars riding the head shard — bit-identical per slot to
    solo ``speculative_generate`` with the SAME tp-sharded params
    (greedy AND sampled), across a join/retire walk, in both KV
    layouts plus the paged-kv8 cell, with compiles == warmup."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.spec_decode import speculative_generate
    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules
    from tf_operator_tpu.serve.engine import ContinuousEngine

    K = 2
    base = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=2,
                d_ff=64, max_seq_len=64, dtype=jnp.float32)
    cfg = TransformerConfig(**base)
    dcfg = TransformerConfig(**{**base, "n_layers": 1})
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    dparams = Transformer(dcfg).init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(mesh, params, param_sharding_rules())
    dsharded = shard_params_by_rules(mesh, dparams,
                                     param_sharding_rules())

    rng = np.random.default_rng(13)
    p1 = rng.integers(0, 64, (1, 9)).astype(np.int32)
    p2 = rng.integers(0, 64, (1, 5)).astype(np.int32)
    failures = 0
    from dataclasses import replace

    cells = [("spec/dense", cfg, dcfg, dict(kv_paged=False)),
             ("spec/paged", cfg, dcfg, dict(kv_paged=True)),
             ("spec/paged-kv8", replace(cfg, kv_int8=True),
              replace(dcfg, kv_int8=True), dict(kv_paged=True))]
    for label, tcfg, tdcfg, kw in cells:
        eng = ContinuousEngine(
            tcfg, params, max_slots=3, kv_block=8, mesh=mesh,
            spec_k=K, draft_cfg=tdcfg, draft_params=dparams, **kw,
        )

        def solo_spec(prompt, steps, temperature=0.0, seed=0):
            skw = {}
            if temperature > 0:
                skw = dict(temperature=temperature,
                           rng=jax.random.PRNGKey(seed))
            out, _ = speculative_generate(
                tcfg, sharded, tdcfg, dsharded, jnp.asarray(prompt),
                steps, k=K, **skw,
            )
            return np.asarray(out)[0]

        plan = {"a": (p1, 10, 0.0, 0), "b": (p2, 6, 0.9, 11)}
        sa = eng.join(jnp.asarray(p1), num_steps=10)
        state = {sa: ("a", 10, [])}
        toks, counts = eng.spec_step()
        for j in range(int(counts[sa])):
            state[sa][2].append(int(toks[sa, j]))
        sb = eng.join(jnp.asarray(p2), num_steps=6, temperature=0.9,
                      seed=11)
        state[sb] = ("b", 6, [])
        done: dict = {}
        for _ in range(40):
            if not state:
                break
            toks, counts = eng.spec_step()
            for s in list(state):
                name, n, acc = state[s]
                for j in range(int(counts[s])):
                    if len(acc) < n:
                        acc.append(int(toks[s, j]))
                if len(acc) >= n:
                    eng.retire(s)
                    done[name] = acc
                    del state[s]
        for name, (p, n, t, seed) in plan.items():
            want = solo_spec(p, n, t, seed)[:n]
            if not np.array_equal(np.asarray(done[name]), want):
                print(f"serve_tp_check: {label} request {name} DIVERGED "
                      f"from solo speculative_generate", file=sys.stderr)
                failures += 1
        if eng.decode_step_compiles != eng.warmup_compiles:
            print(f"serve_tp_check: {label} recompiled "
                  f"({eng.decode_step_compiles} != warmup "
                  f"{eng.warmup_compiles})", file=sys.stderr)
            failures += 1
        print(f"serve_tp_check: {label} ok (k={K}, compiles "
              f"{eng.decode_step_compiles}=warmup, accept_rate "
              f"{eng.spec_debug()['accept_rate']})", flush=True)
    return failures


def run_constrain(tp: int) -> int:
    """Constrained decoding at tp>1 (ISSUE 19): the paged engine on the
    mesh with a grammar-constrained lane co-resident with a free
    sampled lane — the constraint pool's allow/next tables and the
    per-slot FSM vector are REPLICATED (sharding.replicate_put: the
    mask gather reads full vocab rows on every shard, and vocab is
    unsharded), so the constrained lane must be bit-identical to solo
    ``constrained_generate`` with the SAME tp-sharded params, the free
    lane to plain ``generate``, with compiles == warmup."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules
    from tf_operator_tpu.serve.constrain import (
        ConstraintCompiler,
        constrained_generate,
        default_vocab,
    )
    from tf_operator_tpu.serve.engine import ContinuousEngine

    # V=128: the chr-identity vocab must cover ASCII for the grammar.
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(mesh, params, param_sharding_rules())
    comp = ConstraintCompiler(default_vocab(cfg.vocab_size))
    prog = comp.compile({"regex": "[0-9]{2,6}"})

    rng = np.random.default_rng(17)
    p_con = rng.integers(0, 128, (1, 6)).astype(np.int32)
    p_free = rng.integers(0, 128, (1, 9)).astype(np.int32)
    failures = 0
    eng = ContinuousEngine(
        cfg, params, max_slots=2, kv_paged=True, kv_block=8, mesh=mesh,
        constrain_rows=16,
    )
    s_con = eng.join(jnp.asarray(p_con), num_steps=10, program=prog)
    s_free = eng.join(jnp.asarray(p_free), num_steps=10,
                      temperature=0.9, seed=3)
    got = {s_con: [], s_free: []}
    for _ in range(10):
        toks = eng.step()
        for s in got:
            got[s].append(int(toks[s]))
    eng.retire(s_con)
    eng.retire(s_free)
    want_con = np.asarray(constrained_generate(
        cfg, sharded, jnp.asarray(p_con), 10, program=prog
    ))[0]
    want_free = np.asarray(generate(
        cfg, sharded, jnp.asarray(p_free), 10, temperature=0.9,
        rng=jax.random.PRNGKey(3),
    ))[0]
    if not np.array_equal(np.asarray(got[s_con]), want_con):
        print("serve_tp_check: constrain lane DIVERGED from solo "
              "constrained_generate", file=sys.stderr)
        failures += 1
    if not np.array_equal(np.asarray(got[s_free]), want_free):
        print("serve_tp_check: free lane beside the constrained one "
              "DIVERGED from solo generate", file=sys.stderr)
        failures += 1
    if eng.decode_step_compiles != eng.warmup_compiles:
        print(f"serve_tp_check: constrain cell recompiled "
              f"({eng.decode_step_compiles} != warmup "
              f"{eng.warmup_compiles})", file=sys.stderr)
        failures += 1
    print(f"serve_tp_check: constrain/paged ok (compiles "
          f"{eng.decode_step_compiles}=warmup, "
          f"{eng.constrain_debug()['rows_used']} pool rows)",
          flush=True)
    return failures


def run_pallas(tp: int) -> int:
    """Paged-attention kernel at tp>1 (ISSUE 18): the pallas attend
    runs under shard_map over the tp axis (a pallas call has no SPMD
    partitioning rule) with the pool head-sharded and ZERO collectives
    inside the attend. Proves, for {f32, kv8} x pallas:

    - engine output bit-identical to solo ``generate`` with the SAME
      tp-sharded params, across a join/retire occupancy walk with a
      sampled slot;
    - the cache leaf SET (paths, shapes, dtypes) is identical to the
      gather engine's — the kernel's scratch is pallas-internal, so
      serve/sharding.py's supervisor-rebuild reconstruction needs no
      new rules (the regression this guards);
    - the KV pool is really head-sharded (KV/tp per device) and
      ``decode_step_compiles == warmup_compiles`` at the end;
    - a supervised pallas engine crashed mid-decode rebuilds through
      the factory and replays bit-identically without a second
      compile."""
    from dataclasses import replace

    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.faultinject import FaultInjector
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(mesh, params, param_sharding_rules())

    def leafset(tree):
        return {
            (jax.tree_util.keystr(path), leaf.shape, str(leaf.dtype))
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                tree
            )[0]
        }

    rng = np.random.default_rng(21)
    p1 = rng.integers(0, 64, (1, 9)).astype(np.int32)
    p2 = rng.integers(0, 64, (1, 5)).astype(np.int32)
    failures = 0
    for label, tcfg in (("pallas/f32", cfg),
                        ("pallas/kv8", replace(cfg, kv_int8=True))):
        eng = ContinuousEngine(
            tcfg, params, max_slots=3, kv_paged=True, kv_block=8,
            mesh=mesh, kv_attend="pallas",
        )
        gather = ContinuousEngine(
            tcfg, params, max_slots=3, kv_paged=True, kv_block=8,
            mesh=mesh,
        )
        if leafset(eng._cache) != leafset(gather._cache):
            print(f"serve_tp_check: {label} cache leaf set differs "
                  f"from the gather engine's — sharding.py's rebuild "
                  f"rules no longer cover it", file=sys.stderr)
            failures += 1
        del gather
        kv_pool = [
            leaf for path, leaf
            in jax.tree_util.tree_flatten_with_path(eng._cache)[0]
            if "pool_key" in jax.tree_util.keystr(path)
        ][0]
        local_kv = kv_pool.addressable_shards[0].data.shape[-2]
        if local_kv != cfg.kv_heads // tp:
            print(f"serve_tp_check: {label} per-device pool shard "
                  f"holds {local_kv} KV heads, expected "
                  f"{cfg.kv_heads // tp}", file=sys.stderr)
            failures += 1

        def solo(prompt, steps, *, temperature=0.0, seed=0):
            kw = {}
            if temperature > 0:
                kw = dict(temperature=temperature,
                          rng=jax.random.PRNGKey(seed))
            return np.asarray(
                generate(tcfg, sharded, jnp.asarray(prompt), steps,
                         **kw)
            )[0]

        plan = {"a": (p1, 10, 0.0, 0), "b": (p2, 6, 0.0, 0),
                "c": (p1, 8, 0.9, 3)}
        joins = {2: "b", 5: "c"}
        live, outs = {}, {}
        live[eng.join(jnp.asarray(p1), num_steps=10)] = ("a", 10, [])
        i = 0
        while live:
            toks = eng.step()
            i += 1
            for s in list(live):
                name, n, acc = live[s]
                acc.append(int(toks[s]))
                if len(acc) == n:
                    eng.retire(s)
                    outs[name] = acc
                    del live[s]
            if i in joins:
                name = joins[i]
                p, n, t, seed = plan[name]
                s = eng.join(jnp.asarray(p), num_steps=n,
                             temperature=t, seed=seed)
                assert s is not None, f"{label}: no slot for {name}"
                live[s] = (name, n, [])
        for name, (p, n, t, seed) in plan.items():
            want = solo(p, n, temperature=t, seed=seed)
            if not np.array_equal(np.asarray(outs[name]), want):
                print(f"serve_tp_check: {label} request {name} "
                      f"DIVERGED from solo generate", file=sys.stderr)
                failures += 1
        if eng.decode_step_compiles != eng.warmup_compiles:
            print(f"serve_tp_check: {label} recompiled "
                  f"({eng.decode_step_compiles} != warmup "
                  f"{eng.warmup_compiles})", file=sys.stderr)
            failures += 1
        print(f"serve_tp_check: {label} ok (kv/device {local_kv}, "
              f"leaf set == gather, compiles "
              f"{eng.decode_step_compiles}=warmup)", flush=True)

    # Supervisor rebuild with the kernel in the loop: the rebuilt
    # engine's cache reconstructs through the SAME sharding.py rules
    # (no kernel-side leaves to miss) and replays without recompiling.
    inj = FaultInjector(seed=3)
    sup = EngineSupervisor(
        lambda: ContinuousEngine(cfg, params, max_slots=2, kv_block=8,
                                 kv_paged=True, mesh=mesh,
                                 kv_attend="pallas", faults=inj),
        resilience=ResilienceConfig(watchdog_stall_s=10.0,
                                    restart_backoff_s=0.05,
                                    max_restarts=3),
        faults=inj,
    )
    try:
        prompt = np.random.default_rng(17).integers(
            0, cfg.vocab_size, (1, 11)
        ).astype(np.int32)
        want = np.asarray(
            generate(cfg, sharded, jnp.asarray(prompt), 20)
        )
        inj.arm(f"step_raise@{inj.invocations['step_raise'] + 5}")
        out = sup.submit(prompt, 20, timeout=180)
        if sup.restarts != 1:
            print(f"serve_tp_check: pallas replay expected 1 restart, "
                  f"got {sup.restarts}", file=sys.stderr)
            failures += 1
        if not np.array_equal(out, want):
            print("serve_tp_check: pallas post-crash replay != solo",
                  file=sys.stderr)
            failures += 1
        if sup.engine.decode_step_compiles != \
                sup.engine.warmup_compiles:
            print("serve_tp_check: rebuilt pallas engine recompiled",
                  file=sys.stderr)
            failures += 1
        if not failures:
            print(f"serve_tp_check: pallas supervisor replay ok "
                  f"(1 restart, replay bit-identical)", flush=True)
    finally:
        sup.stop(timeout=30.0)
    return failures


def run_supervisor_replay(tp: int) -> int:
    """Crash a supervised tp engine mid-decode: the rebuild reconstructs
    the mesh (same factory, same shardings) and the replay is
    bit-identical."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
        param_sharding_rules,
    )
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import shard_params_by_rules
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.faultinject import FaultInjector
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = create_mesh({"tp": tp}, jax.devices()[:tp])
    sharded = shard_params_by_rules(mesh, params, param_sharding_rules())
    inj = FaultInjector(seed=1)
    sup = EngineSupervisor(
        lambda: ContinuousEngine(cfg, params, max_slots=2, kv_block=8,
                                 mesh=mesh, faults=inj),
        resilience=ResilienceConfig(watchdog_stall_s=10.0,
                                    restart_backoff_s=0.05,
                                    max_restarts=3),
        faults=inj,
    )
    try:
        prompt = np.random.default_rng(9).integers(
            0, cfg.vocab_size, (1, 11)
        ).astype(np.int32)
        want = np.asarray(
            generate(cfg, sharded, jnp.asarray(prompt), 24)
        )
        if not np.array_equal(sup.submit(prompt, 24), want):
            print("serve_tp_check: pre-crash output != solo",
                  file=sys.stderr)
            return 1
        inj.arm(f"step_raise@{inj.invocations['step_raise'] + 6}")
        out = sup.submit(prompt, 24, timeout=180)
        if sup.restarts != 1:
            print(f"serve_tp_check: expected 1 restart, got "
                  f"{sup.restarts}", file=sys.stderr)
            return 1
        if not np.array_equal(out, want):
            print("serve_tp_check: post-crash replay != solo",
                  file=sys.stderr)
            return 1
        if sup.engine.decode_step_compiles != \
                sup.engine.warmup_compiles:
            print("serve_tp_check: rebuilt engine recompiled",
                  file=sys.stderr)
            return 1
        if sup.mesh_devices != tp:
            print(f"serve_tp_check: rebuilt mesh width "
                  f"{sup.mesh_devices} != {tp}", file=sys.stderr)
            return 1
        print(f"serve_tp_check: supervisor replay ok (1 restart, "
              f"mesh reconstructed at {tp} devices, replay "
              f"bit-identical)", flush=True)
        return 0
    finally:
        sup.stop(timeout=30.0)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tp", type=int, default=2,
                   help="mesh width (forced as CPU host devices when "
                        "the platform is CPU)")
    p.add_argument("--skip-supervisor", action="store_true",
                   help="matrix only (the replay drill builds 2+ more "
                        "engines)")
    args = p.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _force_host_devices(args.tp)
    failures = run_matrix(args.tp)
    failures += run_spec(args.tp)
    failures += run_constrain(args.tp)
    failures += run_pallas(args.tp)
    if not args.skip_supervisor:
        failures += run_supervisor_replay(args.tp)
    if failures:
        print(f"serve_tp_check: FAIL ({failures} failure(s))",
              file=sys.stderr)
        return 1
    print(f"serve_tp_check: OK (tp={args.tp}, matrix + spec "
          f"+ constrain + pallas + supervisor replay bit-identical, "
          f"zero post-warmup recompiles)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
