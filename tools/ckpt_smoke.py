#!/usr/bin/env python
"""Fast checkpoint-coordination smoke: runs the `ckpt`-marked tests in
isolation (protocol/registry/GC units, the executor ack relay with real
processes, and the graceful-eviction barrier chaos cases on both cluster
backends) — the ~30s loop for iterating on tf_operator_tpu/ckpt/ without
paying for the whole tier-1 run. Mirrors tools/sched_smoke.py and
tools/health_smoke.py.

    python tools/ckpt_smoke.py             # the smoke subset
    python tools/ckpt_smoke.py -k barrier  # extra pytest args pass through

Exit code is pytest's. The same tests also run (unmarked-slow, so by
default) inside the tier-1 command in ROADMAP.md.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [
        sys.executable, "-m", "pytest",
        "tests/test_ckpt.py", "tests/test_ckpt_chaos.py",
        "-m", "ckpt",
        "-q", "-p", "no:cacheprovider",
        *args,
    ]
    return subprocess.call(cmd, cwd=REPO_ROOT, env=env)


if __name__ == "__main__":
    raise SystemExit(main())
