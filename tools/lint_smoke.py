#!/usr/bin/env python
"""Fast tpulint smoke: the whole static-analysis story in ~30s —

1. the `lint`-marked tests (fixture exactness per pass, waiver grammar,
   class/lock-model units, checks CLI, witness wrap/inertness);
2. the repo gate itself: the FULL pass set (syntax, unused-import,
   lock-order, guarded-attr, blocking-under-lock, metrics-registry,
   typed-error) over the whole tree must be green and finish inside the
   15s CI budget.

    python tools/lint_smoke.py             # tests + repo gate
    python tools/lint_smoke.py -k waiver   # extra pytest args pass through
    python tools/lint_smoke.py --gate-only # just the repo gate + timing

The runtime lock-order witness's chaos assertions live in
tests/test_serve_chaos.py / test_fleet_chaos.py (serve/fleet smokes).

Exit code: non-zero if the tests fail, the gate finds problems, or the
gate blows the time budget.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE_BUDGET_S = 15.0


def run_gate() -> int:
    sys.path.insert(0, REPO_ROOT)
    from tf_operator_tpu.harness.checks import run_checks

    t0 = time.monotonic()
    problems = run_checks(root=REPO_ROOT)
    dt = time.monotonic() - t0
    for p in problems:
        print(p, file=sys.stderr)
    print(f"lint gate: {len(problems)} problem(s) in {dt:.1f}s "
          f"(budget {GATE_BUDGET_S:.0f}s)")
    if problems:
        return 1
    if dt > GATE_BUDGET_S:
        print(f"lint gate: TOO SLOW ({dt:.1f}s > {GATE_BUDGET_S:.0f}s)",
              file=sys.stderr)
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if "--gate-only" in args:
        args.remove("--gate-only")
        return run_gate()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [
        sys.executable, "-m", "pytest",
        "tests/test_lint.py", "tests/test_ci_tooling.py",
        "-m", "not slow",
        "-q", "-p", "no:cacheprovider",
        *args,
    ]
    rc = subprocess.call(cmd, cwd=REPO_ROOT, env=env)
    if rc != 0:
        return rc
    return run_gate()


if __name__ == "__main__":
    raise SystemExit(main())
