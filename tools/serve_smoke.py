#!/usr/bin/env python
"""Fast continuous-batching smoke: runs the `serve`-marked tests in
isolation (slot-engine exactness vs solo generate, zero-recompile pins,
scheduler drain/EOS/metrics, serve-bench structure) — the quick loop for
iterating on tf_operator_tpu/serve/ without paying for the whole tier-1
run.

    python tools/serve_smoke.py            # the smoke subset
    python tools/serve_smoke.py -k drain   # extra pytest args pass through

Exit code is pytest's. CI wires this as the pre-merge gate for serving
changes; the same tests also run (unmarked-slow, so by default) inside
the tier-1 command in ROADMAP.md.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [
        sys.executable, "-m", "pytest",
        "tests/test_serve_engine.py", "tests/test_serve_sched.py",
        "-m", "serve",
        "-q", "-p", "no:cacheprovider",
        *args,
    ]
    return subprocess.call(cmd, cwd=REPO_ROOT, env=env)


if __name__ == "__main__":
    raise SystemExit(main())
