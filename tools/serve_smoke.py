#!/usr/bin/env python
"""Fast continuous-batching smoke: runs the `serve`-marked tests in
isolation (slot-engine exactness vs solo generate, paged-cache/CoW/
prefix-sharing pins, KV-tier spill/restore pins, constrained-decoding
grammar/bit-identity pins, zero-recompile pins,
scheduler drain/EOS/metrics,
serve-bench structure), then one INLINE end-to-end pair through a live
paged engine + scheduler — a plain paged request and a shared-prefix
request — asserting both reproduce solo generate bit-for-bit and the
second actually skipped its prefill — then a TRACED request through a
supervised engine (queue/admit/prefill/decode-interval spans under one
request id, in phase order, valid Chrome-trace export) — then a
CONSTRAINED end-to-end through a supervised engine (grammar_complete
JSON that parses, typed invalid_grammar 400 on a malformed spec, crash
replay bit-identical to solo constrained_generate) — and finally the SPMD
tensor-parallel matrix (tools/serve_tp_check.py at tp=2 host devices:
{dense, paged} x {one-shot, chunked} bit-identity, the batch-wide
speculative cells spec/{dense, paged, paged-kv8}, a constrained cell,
+ the supervisor
mesh-reconstruction replay, slow-marked in tier-1 so THIS is its
default home) and the POD-SCALE {tp=2, dp=2} pass (serve_tp_check.py
--dp 2 at 4 host devices: one engine over the 2-D mesh, dense/paged/
kv8/pallas bit-identity, dp-shard KV ingest, 2-D supervisor replay).
The quick loop for iterating on tf_operator_tpu/serve/
without paying for the whole tier-1 run.

    python tools/serve_smoke.py            # the smoke subset + e2e pair
    python tools/serve_smoke.py -k drain   # extra pytest args pass through
    python tools/serve_smoke.py --chaos    # resilience chaos pass

``--chaos`` is the resilience fast-pass: the FULL chaos matrix from
tests/test_serve_chaos.py (every fault point x {one-shot, chunked} x
{dense, paged} — including the combos tier-1 carries under the slow
marker) plus an inline kill-mid-run e2e through a live supervised
engine, asserting the watchdog replay is bit-identical and nothing is
lost. The serve_bench chaos-mix structural test rides the same marker.

Exit code is pytest's (or 1 if the e2e pair fails). CI wires this as
the pre-merge gate for serving changes; the same tests also run
(unmarked-slow, so by default) inside the tier-1 command in ROADMAP.md.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def paged_e2e_pair() -> int:
    """One paged + one shared-prefix request end-to-end: live engine,
    live serving loop, outputs pinned against solo generate, prefix
    reuse proven by the engine's own counters."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
    )
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.scheduler import ContinuousScheduler

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = ContinuousEngine(
        cfg, params, max_slots=2, kv_paged=True, kv_block=8
    )
    sched = ContinuousScheduler(engine).start()
    try:
        import threading
        import time

        prompt = np.random.default_rng(5).integers(
            0, cfg.vocab_size, (1, 13)
        ).astype(np.int32)
        steps = 30
        want = np.asarray(
            generate(cfg, params, jnp.asarray(prompt), steps)
        )
        # Prefix reuse spans LIVE requests: submit the donor on a
        # thread, wait until it owns a slot (its prompt blocks are
        # registered), then submit the identical prompt — an exact
        # match that skips prefill and CoWs its partial last block.
        first: dict = {}

        def donor():
            first["out"] = sched.submit(prompt, steps)

        t = threading.Thread(target=donor)
        t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and engine.active_slots < 1:
            time.sleep(0.005)
        assert engine.active_slots >= 1, "donor never reached a slot"
        second = sched.submit(prompt, steps)  # exact shared-prefix reuse
        t.join(timeout=60)
        assert np.array_equal(first.get("out"), want), \
            "paged output != solo"
        assert np.array_equal(second, want), "shared-prefix output != solo"
        assert engine.prefill_tokens_saved >= prompt.shape[1], (
            "shared-prefix admission did not skip its prefill"
        )
        assert engine.decode_step_compiles == engine.warmup_compiles
        print(
            f"serve_smoke: paged + shared-prefix e2e pair ok "
            f"(saved {engine.prefill_tokens_saved} prefill tokens, "
            f"{engine.cow_copies} CoW copies)", flush=True,
        )
        return 0
    finally:
        sched.stop(timeout=30.0)


def trace_e2e() -> int:
    """One traced request through a SUPERVISED engine: the default-on
    data-plane tracer yields the queue → admit → prefill → decode span
    chain under the request's id, in phase order, with the decode steps
    aggregated into interval spans — and /debug/traces-shaped export
    stays valid JSON."""
    import json

    import numpy as np
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )
    from tf_operator_tpu.runtime.tracing import SERVE_TRACER
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
    )
    from tf_operator_tpu.serve.scheduler import ServeRequest

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    SERVE_TRACER.clear()
    sup = EngineSupervisor(
        lambda: ContinuousEngine(cfg, params, max_slots=2, kv_block=8,
                                 prefill_chunk=4),
        resilience=ResilienceConfig(),
    )
    try:
        prompt = np.random.default_rng(3).integers(
            0, cfg.vocab_size, (1, 9)
        ).astype(np.int32)
        req = sup.submit_request(
            ServeRequest(prompt, 16, request_id="smoke-trace")
        )
        assert len(req.out) == 16
        mine = [s for s in SERVE_TRACER.spans()
                if s.attrs.get("request_id") == "smoke-trace"]
        names = [s.name for s in mine]
        for expected in ("queue.wait", "admit.plan"):
            assert expected in names, (expected, names)
        assert any(n.startswith("prefill") for n in names), names
        decode = [s for s in mine if s.name == "decode.interval"]
        assert decode, names
        assert sum(int(s.attrs["tokens"]) for s in decode) == 16
        # Parentage by time: queue closes before the plan opens, the
        # plan before prefill, prefill before the first decode interval.
        start = {n: min(s.start_us for s in mine if s.name == n)
                 for n in set(names)}
        pf = min(v for n, v in start.items() if n.startswith("prefill"))
        assert (start["queue.wait"] <= start["admit.plan"] <= pf
                <= start["decode.interval"])
        json.loads(SERVE_TRACER.export_chrome_trace())  # valid export
        print(
            f"serve_smoke: trace e2e ok ({len(mine)} spans for one "
            f"request, {len(decode)} decode interval(s))", flush=True,
        )
        return 0
    finally:
        sup.stop(timeout=30.0)


def chaos_e2e() -> int:
    """Kill the decode step mid-run through a LIVE supervised engine:
    the watchdog rebuilds, the in-flight greedy request replays
    bit-identical to solo generate, nothing is lost, and the rebuilt
    engine never recompiles after its warmup."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
    )
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.faultinject import FaultInjector
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    inj = FaultInjector(seed=1)
    sup = EngineSupervisor(
        lambda: ContinuousEngine(cfg, params, max_slots=2, kv_block=8,
                                 faults=inj),
        resilience=ResilienceConfig(watchdog_stall_s=5.0,
                                    restart_backoff_s=0.05,
                                    max_restarts=3),
        faults=inj,
    )
    try:
        prompt = np.random.default_rng(9).integers(
            0, cfg.vocab_size, (1, 11)
        ).astype(np.int32)
        want = np.asarray(generate(cfg, params, jnp.asarray(prompt), 24))
        assert np.array_equal(sup.submit(prompt, 24), want)  # warm
        inj.arm(f"step_raise@{inj.invocations['step_raise'] + 6}")
        out = sup.submit(prompt, 24, timeout=90)
        assert sup.restarts == 1, sup.restarts
        assert np.array_equal(out, want), "replayed output != solo"
        assert sup.engine.decode_step_compiles == \
            sup.engine.warmup_compiles
        print("serve_smoke: chaos e2e ok (1 restart, replay "
              "bit-identical, zero post-warmup recompiles)", flush=True)
        return 0
    finally:
        sup.stop(timeout=30.0)


def constrain_e2e() -> int:
    """Structured decoding end-to-end through a LIVE supervised engine
    (ISSUE 19): a JSON-schema-constrained request retires
    grammar_complete with output that json.loads, a malformed spec is a
    typed invalid_grammar 400 AT ENQUEUE (no device work), and a step
    crash mid-constrained-run replays bit-identical to solo
    constrained_generate through the watchdog rebuild — the stamped
    program survives the supervisor's requeue and re-binds into the
    rebuilt engine's fresh pool."""
    import json

    import numpy as np
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )
    from tf_operator_tpu.serve.constrain import (
        ConstraintCompiler,
        constrained_generate,
        default_vocab,
        detokenize,
        walk_tokens,
    )
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.faultinject import FaultInjector
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        InvalidGrammar,
        ResilienceConfig,
    )
    from tf_operator_tpu.serve.scheduler import ServeRequest

    # V=128: the chr-identity vocab must cover ASCII for JSON grammars.
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    vocab = default_vocab(cfg.vocab_size)
    comp = ConstraintCompiler(vocab)
    inj = FaultInjector(seed=2)
    sup = EngineSupervisor(
        lambda: ContinuousEngine(cfg, params, max_slots=2, kv_block=8,
                                 constrain_rows=32, faults=inj),
        resilience=ResilienceConfig(watchdog_stall_s=5.0,
                                    restart_backoff_s=0.05,
                                    max_restarts=3),
        faults=inj,
        constrainer=comp,
    )
    try:
        spec = {"json_schema": {
            "type": "object",
            "properties": {"name": {"type": "string", "maxLength": 4},
                           "ok": {"type": "boolean"}},
            "required": ["name", "ok"],
        }}
        prompt = np.random.default_rng(4).integers(
            0, cfg.vocab_size, (1, 8)
        ).astype(np.int32)
        prog = comp.compile(spec)
        want = np.asarray(constrained_generate(
            cfg, params, jnp.asarray(prompt), 32, program=prog
        ))[0]
        _, done = walk_tokens(prog, [int(t) for t in want])
        assert done is not None, "bounded grammar must complete"
        want = [int(t) for t in want[: done + 1]]

        req = sup.submit_request(ServeRequest(prompt, 32,
                                              constrain=spec))
        assert req.finish_reason == "grammar_complete", req.finish_reason
        assert list(req.out) == want, "constrained output != solo"
        doc = json.loads(detokenize(vocab, req.out))
        assert isinstance(doc["ok"], bool), doc

        try:
            sup.submit_request(ServeRequest(prompt, 4,
                                            constrain={"regex": "[bad"}))
            raise AssertionError("malformed spec was accepted")
        except InvalidGrammar as exc:
            assert exc.http_status == 400 and not exc.retryable

        inj.arm(f"step_raise@{inj.invocations['step_raise'] + 3}")
        req2 = sup.submit_request(ServeRequest(prompt, 32,
                                               constrain=spec))
        assert sup.restarts == 1, sup.restarts
        assert list(req2.out) == want, "replayed constrained != solo"
        assert req2.finish_reason == "grammar_complete"
        assert sup.engine.decode_step_compiles == \
            sup.engine.warmup_compiles
        print(
            "serve_smoke: constrain e2e ok (grammar_complete JSON "
            "parses, typed 400 on the bad spec, crash replay "
            "bit-identical, zero post-warmup recompiles)", flush=True,
        )
        return 0
    finally:
        sup.stop(timeout=30.0)


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    chaos = "--chaos" in args
    if chaos:
        args.remove("--chaos")
    if chaos:
        cmd = [
            sys.executable, "-m", "pytest",
            "tests/test_serve_chaos.py",
            "-m", "chaos",  # includes the slow-marked matrix combos
            "-q", "-p", "no:cacheprovider",
            *args,
        ]
    else:
        cmd = [
            sys.executable, "-m", "pytest",
            "tests/test_serve_engine.py", "tests/test_serve_sched.py",
            "tests/test_kvcache_paged.py", "tests/test_serve_chaos.py",
            "tests/test_serve_tier.py", "tests/test_paged_attention.py",
            "tests/test_serve_constrain.py",
            "-m", "serve and not slow",
            "-q", "-p", "no:cacheprovider",
            *args,
        ]
    rc = subprocess.call(cmd, cwd=REPO_ROOT, env=env)
    if rc != 0:
        return rc
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if chaos:
        return chaos_e2e()
    rc = paged_e2e_pair()
    if rc != 0:
        return rc
    rc = trace_e2e()
    if rc != 0:
        return rc
    rc = constrain_e2e()
    if rc != 0:
        return rc
    # The SPMD tensor-parallel matrix (slow-marked in tier-1, so the
    # smoke is where it runs by default): {dense, paged} x {one-shot,
    # chunked} at tp=2 host devices, bit-identical to solo generate,
    # plus the supervisor mesh-reconstruction replay drill. A
    # subprocess — multi-device CPU needs XLA_FLAGS before jax imports.
    tp_env = dict(env)
    tp_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    tp_env["PYTHONPATH"] = (
        REPO_ROOT + os.pathsep + tp_env.get("PYTHONPATH", "")
    )
    rc = subprocess.call(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "serve_tp_check.py"), "--tp", "2"],
        cwd=REPO_ROOT, env=tp_env,
    )
    if rc != 0:
        return rc
    # Pod-scale decode (ISSUE 20): the {tp=2, dp=2} cells — one engine
    # over a 2-D mesh, slot state + pool block axis sharded over dp,
    # bit-identical to the canonical tp oracle for {dense, paged, kv8,
    # pallas}, shipped/tier-restored KV landing on the seating dp
    # shard, and the supervisor rebuilding the 2-D mesh. Also a
    # subprocess: 4 host devices need their own XLA_FLAGS.
    tpdp_env = dict(tp_env)
    tpdp_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    return subprocess.call(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "serve_tp_check.py"),
         "--tp", "2", "--dp", "2"],
        cwd=REPO_ROOT, env=tpdp_env,
    )


if __name__ == "__main__":
    raise SystemExit(main())
