#!/usr/bin/env python
"""Fast fleet-health smoke: runs the `health`-marked tests in isolation
(cell state machine + cordon-aware placement + drain/migration integration
plus the crash-boundary chaos cases on both cluster backends) — the ~10s
loop for iterating on tf_operator_tpu/health/ without paying for the whole
tier-1 run. Mirrors tools/sched_smoke.py.

    python tools/health_smoke.py            # the smoke subset
    python tools/health_smoke.py -k drain   # extra pytest args pass through

Exit code is pytest's. The same tests also run (unmarked-slow, so by
default) inside the tier-1 command in ROADMAP.md.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [
        sys.executable, "-m", "pytest",
        "tests/test_health.py", "tests/test_health_chaos.py",
        "-m", "health",
        "-q", "-p", "no:cacheprovider",
        *args,
    ]
    return subprocess.call(cmd, cwd=REPO_ROOT, env=env)


if __name__ == "__main__":
    raise SystemExit(main())
