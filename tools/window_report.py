"""Turn a window_autorun artifact directory into the perf attribution report.

Usage: python tools/window_report.py [docs/window_r*/<stamp>]
(default: the newest stamp dir across all docs/window_r* rounds).

Reads each stage's jsonl and derives the quantities VERDICT r3 asked
for, so the analysis of a hardware window is one command:

- measured ceilings (roofline) and every metric re-denominated against
  them (not spec);
- the ResNet split: device-resident synthetic rate vs the end-to-end
  bench rate (compute vs input/transfer attribution), conv-shape
  rooflines vs the matmul ceiling;
- flash attention: 8k ramp/block data vs the 64k line, LM flash-vs-xla;
- LM MFU-vs-size curve; decode int8 vs bf16 and fraction of the measured
  copy roofline.

Prints markdown to stdout — paste into docs/perf.md.
"""

from __future__ import annotations

import json
import os
import sys

V5E_SPEC_TFLOPS = 197.0
V5E_SPEC_GBPS = 819.0


def load(dir_path: str, stage: str) -> list[dict]:
    path = os.path.join(dir_path, f"{stage}.jsonl")
    out = []
    try:
        with open(path) as f:
            for line in f:
                if line.startswith("{"):
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return out


def fmt(x, nd=1):
    return f"{x:.{nd}f}" if isinstance(x, (int, float)) else "—"


def main() -> int:
    docs = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "docs"
    )
    if len(sys.argv) > 1:
        d = sys.argv[1]
    else:
        # Newest stamp dir across every round's window_r* captures.
        import glob

        stamps = sorted(
            glob.glob(os.path.join(docs, "window_r*", "*T*")),
            key=os.path.basename,
        )
        stamps = [s for s in stamps if os.path.isdir(s)]
        if not stamps:
            print("no window_r* artifacts yet")
            return 1
        d = stamps[-1]
    print(f"# Window report — {os.path.basename(d)}\n")

    # Measured ceilings. roofline2 (the re-run with the scan-chained
    # copy leg) overrides the first capture where present.
    roof = (load(d, "roofline") or [{}])[0]
    roof.update((load(d, "roofline2") or [{}])[0])
    chain = roof.get("matmul_chain_tflops")
    copy = roof.get("copy_gbps")
    chain_copy = roof.get("chain_copy_gbps")
    # Bandwidth yardstick: the scan-chained copy where measured (per-
    # execution scheduling makes one-shot copies under-read this
    # environment ~5x — docs/perf.md r05), else the one-shot number.
    bw_roof = chain_copy or copy
    print("## Measured ceilings (same-window)\n")
    print("| probe | value | vs v5e spec |")
    print("|---|---|---|")
    if roof:
        print(f"| dispatch round trip | {fmt(roof.get('dispatch_roundtrip_ms'), 3)} ms | — |")
        for key, val in sorted(roof.items()):
            if key.startswith("matmul_") and key.endswith("_tflops"):
                print(f"| {key} | {fmt(val)} TFLOP/s | "
                      f"{fmt(val / V5E_SPEC_TFLOPS * 100)}% |")
        if copy:
            print(f"| copy bandwidth (one-shot) | {fmt(copy)} GB/s | "
                  f"{fmt(copy / V5E_SPEC_GBPS * 100)}% |")
        if chain_copy:
            print(f"| copy bandwidth (scan-chained) | {fmt(chain_copy)} "
                  f"GB/s | {fmt(chain_copy / V5E_SPEC_GBPS * 100)}% |")
    else:
        print("| (roofline stage produced no data) | | |")
    print()

    # ResNet split.
    syn = (load(d, "synthetic") or [{}])[0]
    bench_lines = load(d, "bench_full")
    resnet = next((m for m in bench_lines
                   if m.get("metric", "").startswith("resnet50_")), {})
    # The dedicated re-measure stages override the first-window lines
    # (bench_resnet2 carries the mfu sanity gate; resnet_resident is the
    # HBM-resident + on-device-augment mode).
    resnet2 = next((m for m in load(d, "bench_resnet2")
                    if m.get("metric", "").startswith("resnet50_")
                    and "error" not in m), {})
    resident = next((m for m in load(d, "resnet_resident")
                     if "resident" in m.get("metric", "")
                     and "error" not in m), {})
    if resnet2:
        resnet = resnet2
    print("## ResNet attribution (VERDICT r3 item 1)\n")
    print("| measurement | img/s |")
    print("|---|---|")
    print(f"| device-resident synthetic (b256) | {fmt(syn.get('images_per_sec'))} |")
    print(f"| device-resident synthetic (b512) | {fmt(syn.get('images_per_sec_b2x'))} |")
    print(f"| end-to-end bench (input+transfer on clock) | {fmt(resnet.get('value'))} |")
    if resident:
        print(f"| resident mode (HBM dataset + on-device augment, "
              f"augmentation on clock) | {fmt(resident.get('value'))} |")
    if syn.get("images_per_sec") and resnet.get("value"):
        ratio = resnet["value"] / syn["images_per_sec"]
        print(f"\nEnd-to-end / synthetic = {fmt(ratio, 2)} — "
              + ("input/transfer owns the gap" if ratio < 0.7
                 else "compute-bound; input path exonerated"))
    if resnet.get("mfu") is not None and chain:
        spec_mfu = resnet.get("mfu", 0.0)
        measured_mfu = spec_mfu * V5E_SPEC_TFLOPS / chain if chain else 0.0
        print(f"\nBench MFU: {fmt(spec_mfu * 100)}% of spec, "
              f"**{fmt(measured_mfu * 100)}% of the measured "
              f"{fmt(chain)} TFLOP/s ceiling** "
              f"(flops_source={resnet.get('flops_source')})")
    conv = (load(d, "convsweep") or [{}])[0]
    conv_rows = [(key.removesuffix("_tflops"), val) for key, val in conv.items()
                 if key.endswith("_tflops")]
    if conv_rows:
        print("\n| conv shape | TFLOP/s | % of measured matmul ceiling |")
        print("|---|---|---|")
        for name, val in conv_rows:
            pct = fmt(val / chain * 100) if chain else "—"
            print(f"| {name} | {fmt(val, 2)} | {pct}% |")
    print()

    # Flash attention.
    print("## Flash attention (VERDICT r3 item 3)\n")
    ramp = (load(d, "flashramp") or [{}])[0]
    if ramp.get("rep_seconds"):
        reps = ramp["rep_seconds"]
        print(f"- 8k/b4 cold-start per-rep seconds: {reps} "
              f"(best {fmt(min(reps[1:]) if len(reps) > 1 else reps[0], 3)}s "
              f"→ {fmt(ramp.get('best_tflops'))} TFLOP/s, "
              f"kernel={ramp.get('kernel')})")
        if max(reps) > 3 * min(reps):
            print("  → strong ramp: earlier single-shot numbers "
                  "under-reported steady state")
    blocks = (load(d, "flashblocks") or [{}])[0]
    bq = {key: val for key, val in blocks.items() if key.startswith("bq")}
    if bq:
        best = max(bq, key=bq.get)
        print(f"- Q-block A/B: " + ", ".join(
            f"{key}={fmt(val)}" for key, val in sorted(bq.items()))
            + f" TFLOP/s → best {best}")
    qb = (load(d, "qblock") or [{}])[0]
    qb_legs = {key.removesuffix("_tflops"): val for key, val in qb.items()
               if key.endswith("_tflops")}
    if qb_legs:
        print(f"- qblock interleaved (auto pair {qb.get('auto_pair')}): "
              + ", ".join(f"{name}={fmt(val)}"
                          for name, val in sorted(qb_legs.items()))
              + " TFLOP/s — dispatch_auto vs its direct_bq leg decides "
                "config-effect vs drift")
    for m in load(d, "bench_full"):
        if m.get("metric", "").startswith("flash_attention"):
            print(f"- bench {m['metric']}: {m['value']} TFLOP/s "
                  f"({fmt(m['value'] / chain * 100) if chain else '—'}% of "
                  f"measured ceiling)")
    ab = {}
    for leg in ("lm_ab_flash", "lm_ab_xla"):
        rows = load(d, leg)
        if rows:
            ab[leg] = rows[0].get("value")
    if len(ab) == 2 and all(ab.values()):
        ratio = ab["lm_ab_flash"] / ab["lm_ab_xla"]
        print(f"- LM A/B: flash {fmt(ab['lm_ab_flash'])} vs xla "
              f"{fmt(ab['lm_ab_xla'])} tok/s → flash is {fmt(ratio, 2)}x "
              + ("(keep flash)" if ratio >= 1 else "(DISPATCH SHOULD FALL "
                 "BACK — flash loses at this shape)"))
    print()

    # LM size sweep.
    print("## LM MFU vs size (VERDICT r3 item 4)\n")
    sweep = load(d, "lmsweep")
    if sweep:
        print("| size | params M | tok/s | spec MFU | measured-ceiling MFU |")
        print("|---|---|---|---|---|")
        for row in sweep:
            if "error" in row:
                print(f"| {row.get('size')} | — | — | error: "
                      f"{row['error'][:40]} | |")
                continue
            mfu = row.get("mfu_spec", 0.0)
            meas = mfu * V5E_SPEC_TFLOPS / chain if chain else None
            print(f"| {row.get('size')} | {fmt(row.get('params_millions'))} "
                  f"| {fmt(row.get('tokens_per_sec'))} "
                  f"| {fmt(mfu * 100)}% | {fmt((meas or 0) * 100)}% |")
    print()

    # Decode.
    print("## Decode (VERDICT r3 item 5)\n")
    rows = load(d, "decodesweep")
    bench_decode = [m for m in bench_lines
                    if m.get("metric", "").startswith("lm_decode")]
    all_rows = rows + bench_decode
    if all_rows:
        bw_label = ("scan-chained copy roofline" if chain_copy
                    else "one-shot copy roofline")
        print(f"| source | weights | batch | gen tok/s | GB/s | % of measured {bw_label} |")
        print("|---|---|---|---|---|---|")
        for row in rows:
            if "error" in row:
                continue
            gbps = row.get("hbm_gbps")
            pct = fmt(gbps / bw_roof * 100) if (gbps and bw_roof) else "—"
            print(f"| probe | {row.get('weights')} | {row.get('batch')} "
                  f"| {fmt(row.get('gen_tokens_per_sec'))} | {fmt(gbps)} "
                  f"| {pct}% |")
        for m in bench_decode:
            gbps = m.get("hbm_gbps")
            pct = fmt(gbps / bw_roof * 100) if (gbps and bw_roof) else "—"
            # lm_decode_gen_tokens_per_sec_{weights}_b{B}_1chip
            parts = m["metric"].split("_")
            weights = parts[6] if len(parts) > 6 else "?"
            print(f"| bench | {weights} | — "
                  f"| {m['value']} | {fmt(gbps)} | {pct}% |")
        bf = next((r for r in rows if r.get("weights") == "bf16"
                   and r.get("batch") == 8 and "error" not in r), None)
        i8 = next((r for r in rows if r.get("weights") == "int8"
                   and r.get("batch") == 8 and "error" not in r), None)
        if (bf and i8 and bf.get("gen_tokens_per_sec")
                and i8.get("gen_tokens_per_sec")):
            sp = i8["gen_tokens_per_sec"] / bf["gen_tokens_per_sec"]
            print(f"\nint8 speedup at b8: **{fmt(sp, 2)}x** "
                  + ("(the VMEM-dequant kernel pays off)" if sp > 1.2
                     else "(below expectation — check kernel dispatch)"))

    # Long-context cache A/B (decodelong): the shape where kv_int8's
    # halved cache read can actually move the headline.
    long_rows = [r for r in load(d, "decodelong") if "error" not in r]
    if long_rows:
        print("\n| context | cache | gen tok/s | mean tok/s | GB/s "
              "| kv fraction of read |")
        print("|---|---|---|---|---|---|")
        for row in long_rows:
            print(f"| {row.get('context')} | {row.get('cache')} "
                  f"| {fmt(row.get('gen_tokens_per_sec'))} "
                  f"| {fmt(row.get('mean_tokens_per_sec'))} "
                  f"| {fmt(row.get('hbm_gbps'))} "
                  f"| {fmt((row.get('kv_read_fraction') or 0) * 100)}% |")
        lb = next((r for r in long_rows if r.get("cache") == "bf16"), None)
        l8 = next((r for r in long_rows if r.get("cache") == "kv8"), None)
        if (lb and l8 and lb.get("gen_tokens_per_sec")
                and l8.get("gen_tokens_per_sec")):
            sp = l8["gen_tokens_per_sec"] / lb["gen_tokens_per_sec"]
            print(f"\nkv8 long-context speedup: **{fmt(sp, 2)}x** "
                  + ("(cache-read halving pays off)" if sp > 1.15
                     else "(cache term not dominant here — check "
                          "kv_read_fraction)"))

    # Speculative decoding component costs (acceptance-curve endpoints).
    spec = (load(d, "specdecode") or [{}])[0]
    if spec.get("tokens_per_sec_plain"):
        print("\n## Speculative decoding (models/spec_decode.py)\n")
        plain_tps = spec["tokens_per_sec_plain"]
        print("| leg | gen tok/s | vs plain | tokens/round |")
        print("|---|---|---|---|")
        print(f"| plain greedy | {fmt(plain_tps)} | 1.00x | 1 |")
        seg_tps = spec.get("tokens_per_sec_segmented")
        if seg_tps:
            print(f"| segmented (streaming path) | {fmt(seg_tps)} "
                  f"| {fmt(seg_tps / plain_tps, 2)}x | — |")
        for leg, tpr in (("spec_self", "tokens_per_round_self"),
                         ("spec_cold", "tokens_per_round_cold")):
            tps = spec.get(f"tokens_per_sec_{leg}")
            if tps:
                print(f"| {leg} (k={spec.get('k')}) | {fmt(tps)} "
                      f"| {fmt(tps / plain_tps, 2)}x "
                      f"| {fmt(spec.get(tpr), 2)} |")
        print("\nself = 100% acceptance at full draft cost (mechanics "
              "ceiling); cold = ~0% acceptance (floor). A trained "
              "draft/target pair lands between per the cost model in "
              "the probe docstring.")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
