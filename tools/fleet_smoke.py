#!/usr/bin/env python
"""Fast fleet-serving smoke: runs the `fleet`-marked tests in isolation
(the jax-free membership/router/autoscale decision tier plus the
controller kill/cordon/drain/rolling chaos on both cluster backends) —
the ~20s loop for iterating on tf_operator_tpu/fleet/ without paying
for the whole tier-1 run.

    python tools/fleet_smoke.py            # the smoke subset
    python tools/fleet_smoke.py --bench    # + the serve_bench fleet e2e
                                           # (real engines, ~2 min)
    python tools/fleet_smoke.py -k router  # extra pytest args pass through

Exit code is pytest's. CI wires this as the pre-merge gate for fleet
changes; the same tests also run (unmarked-slow, so by default) inside
the tier-1 command in ROADMAP.md.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    marker = "fleet"
    if "--bench" in args:
        args.remove("--bench")
    else:
        marker = "fleet and not slow"
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [
        sys.executable, "-m", "pytest",
        "tests/test_fleet.py", "tests/test_fleet_chaos.py",
        "-m", marker,
        "-q", "-p", "no:cacheprovider",
        *args,
    ]
    return subprocess.call(cmd, cwd=REPO_ROOT, env=env)


if __name__ == "__main__":
    raise SystemExit(main())
