#!/usr/bin/env python
"""Mixed-length open-loop serving bench: continuous batching vs the
legacy batch-window coalescer, same model, same seeded traffic.

The lm_decode bench line is a static-batch best case (one shape, lock
step, batch 8); THIS is the serving number: requests with ≥4 distinct
(prompt_len, num_steps) shapes arrive on a deterministic seeded open-loop
schedule (arrival times are data, independent of service rate — the
honest serving-load model), and each engine serves the identical
schedule. Both legs get one untimed dry run of the whole schedule first
(every executable warm), then the timed run measures steady-state
serving — so the comparison is engine mechanics (occupancy vs lock-step
coalescing), not compile luck.

Emits one BENCH-style JSON line per leg:

    {"metric": "serve_continuous_tokens_per_sec_mixed", "value": ...,
     "vs_baseline": <continuous / coalesce>, "ttft_p50_ms": ...,
     "ttft_p99_ms": ..., "mean_occupancy": ..., "steady_occupancy": ...}

vs_baseline on the continuous line is the speedup over the coalesce leg
(the acceptance ratio); ttft on the coalesce line is full-response
latency (lock-step clients see nothing earlier). steady_occupancy is the
mean active-slot fraction over the middle half of decode steps — the
window where admission has filled and drain has not started.

All randomness is seeded (schedule, prompts); wall-clock only enters the
timing fields, so tests assert structure and token counts, never timing.
BENCH_SMOKE shrinks shapes for CI. Run:

    JAX_PLATFORMS=cpu python tools/serve_bench.py            # both legs
    python tools/serve_bench.py --engine continuous          # one leg
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402

# (prompt_len, num_steps) mix — ≥4 shapes spanning short/long prompts and
# short/long horizons, so lock-step coalescing has real stragglers.
SHAPES = [(8, 24), (16, 48), (32, 16), (4, 64)]
SMOKE_SHAPES = [(4, 6), (8, 10), (12, 4), (2, 12)]


def build_schedule(n_requests: int, mean_gap_ms: float, seed: int,
                   shapes, vocab: int):
    """Deterministic open-loop traffic: [(t_offset_s, prompt, steps)]."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for _ in range(n_requests):
        p, steps = shapes[int(rng.integers(0, len(shapes)))]
        prompt = rng.integers(0, vocab, (1, p)).astype(np.int32)
        out.append((t, prompt, steps))
        t += float(rng.exponential(mean_gap_ms)) / 1e3
    return out


def run_schedule(schedule, submit_fn):
    """Replay the schedule open-loop (one client thread per request,
    sleeping to its arrival time). Returns (wall_seconds, results):
    results[i] = dict(tokens, latency_s, ttft_s | None, error | None)."""
    results = [None] * len(schedule)
    start = time.perf_counter() + 0.05  # common epoch for all arrivals

    def client(i, offset, prompt, steps):
        delay = start + offset - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t0 = time.perf_counter()
        try:
            tokens, ttft = submit_fn(prompt, steps)
            results[i] = {
                "tokens": tokens,
                "latency_s": time.perf_counter() - t0,
                "ttft_s": ttft if ttft is not None
                else time.perf_counter() - t0,
                "error": None,
            }
        except Exception as exc:  # noqa: BLE001 — one failed request
            # must not hang the bench join below.
            results[i] = {"tokens": None, "latency_s": 0.0,
                          "ttft_s": 0.0, "error": repr(exc)}

    threads = [
        threading.Thread(target=client, args=(i, off, prompt, steps))
        for i, (off, prompt, steps) in enumerate(schedule)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600.0)
    return time.perf_counter() - t0, results


def percentile(values, q):
    if not values:
        return 0.0
    vals = sorted(values)
    return vals[min(len(vals) - 1, int(q * len(vals)))]


def leg_summary(name, wall_s, results, extra):
    errors = [r["error"] for r in results if r and r["error"]]
    tokens = sum(len(r["tokens"]) for r in results if r and r["tokens"]
                 is not None)
    ttfts = [r["ttft_s"] for r in results if r and r["error"] is None]
    lats = [r["latency_s"] for r in results if r and r["error"] is None]
    line = {
        "metric": f"serve_{name}_tokens_per_sec_mixed",
        "value": round(tokens / wall_s, 1) if wall_s else 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "requests": len(results),
        "errors": len(errors),
        "generated_tokens": tokens,
        "wall_seconds": round(wall_s, 3),
        "ttft_p50_ms": round(percentile(ttfts, 0.5) * 1e3, 1),
        "ttft_p99_ms": round(percentile(ttfts, 0.99) * 1e3, 1),
        "latency_p50_ms": round(percentile(lats, 0.5) * 1e3, 1),
        "latency_p99_ms": round(percentile(lats, 0.99) * 1e3, 1),
    }
    line.update(extra)
    if errors:
        line["first_error"] = errors[0]
    return line


def run_continuous(cfg, params, schedule, args) -> dict:
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.scheduler import (
        ContinuousScheduler,
        ServeRequest,
    )

    # ONE engine for both passes: the dry run warms ITS jit caches (the
    # whole point — a fresh engine would recompile on the clock and the
    # line would measure compiles, not serving).
    engine = ContinuousEngine(
        cfg, params, max_slots=args.max_batch,
        prefill_chunk=args.prefill_chunk or None,
    )
    sched = ContinuousScheduler(
        engine, prefill_tokens_per_step=args.prefill_budget
    ).start()

    def submit(prompt, steps):
        req = sched.submit_request(ServeRequest(prompt, steps))
        return list(req.out), req.ttft

    run_schedule(schedule, submit)  # untimed warmup
    sched.reset_stats()
    wall_s, results = run_schedule(schedule, submit)
    steady = list(sched.step_log)
    mid = steady[len(steady) // 4: max(len(steady) // 4 + 1,
                                       3 * len(steady) // 4)]
    stats = {
        "mean_occupancy": round(sched.mean_occupancy, 3),
        "steady_occupancy": round(
            sum(mid) / len(mid) / engine.max_slots, 3
        ) if mid else 0.0,
        "decode_steps": sched.decode_steps,
        "decode_step_compiles": engine.decode_step_compiles,
        "max_batch": engine.max_slots,
        "prefill_chunk": args.prefill_chunk or None,
    }
    sched.stop(timeout=30.0)
    return leg_summary("continuous", wall_s, results, stats)


def run_coalesce(cfg, params, schedule, args) -> dict:
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import generate
    from tf_operator_tpu.serve.coalesce import Coalescer

    lock = threading.Lock()

    def decode_fn(rows, num_steps):
        with lock:
            return generate(cfg, params, rows, num_steps=num_steps)

    def one_pass(timed: bool):
        stop = threading.Event()
        co = Coalescer(args.window_ms / 1e3, args.max_batch, decode_fn,
                       stop)
        t = threading.Thread(target=co.loop, daemon=True)
        t.start()

        def submit(prompt, steps):
            out = co.submit(jnp.asarray(prompt), steps)
            # Lock-step: the client sees nothing before the whole batch
            # finishes — TTFT is response latency (None → measured by
            # the caller).
            return np.asarray(out)[0].tolist(), None

        wall_s, results = run_schedule(schedule, submit)
        stats = {
            "coalesced_batches": co.batches,
            "max_batch_rows": co.max_rows_seen,
            "window_ms": args.window_ms,
            "max_batch": args.max_batch,
        }
        stop.set()
        t.join(timeout=30.0)
        return wall_s, results, stats

    one_pass(timed=False)
    wall_s, results, stats = one_pass(timed=True)
    return leg_summary("coalesce", wall_s, results, stats)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--engine", choices=("continuous", "coalesce", "both"),
                   default="both")
    p.add_argument("--requests", type=int, default=None)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mean-gap-ms", type=float, default=None,
                   help="mean open-loop interarrival gap (seeded "
                        "exponential)")
    p.add_argument("--window-ms", type=float, default=25.0,
                   help="coalesce leg's batch window")
    p.add_argument("--prefill-chunk", type=int, default=16)
    p.add_argument("--prefill-budget", type=int, default=64)
    p.add_argument("--d-model", type=int, default=None)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=128)
    args = p.parse_args(argv)

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    shapes = SMOKE_SHAPES if smoke else SHAPES
    if args.requests is None:
        args.requests = 12 if smoke else 48
    if args.mean_gap_ms is None:
        args.mean_gap_ms = 2.0 if smoke else 5.0
    if args.d_model is None:
        args.d_model = 32 if smoke else 64
    if smoke:
        args.prefill_chunk = min(args.prefill_chunk, 4)

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )

    max_seq = max(p_ + s for p_, s in shapes)
    if args.prefill_chunk:
        max_seq = max(
            max_seq,
            max(-(-p_ // args.prefill_chunk) * args.prefill_chunk + s
                for p_, s in shapes),
        )
    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=4,
        n_layers=args.layers, d_ff=args.d_model * 2,
        # Static cache rows per slot: the largest shape plus headroom,
        # rounded up — the cache read scales with this, as in serving.
        max_seq_len=max(64, 1 << (max_seq - 1).bit_length()),
        dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    schedule = build_schedule(
        args.requests, args.mean_gap_ms, args.seed, shapes, args.vocab
    )

    lines = []
    if args.engine in ("continuous", "both"):
        lines.append(run_continuous(cfg, params, schedule, args))
    if args.engine in ("coalesce", "both"):
        lines.append(run_coalesce(cfg, params, schedule, args))
    if len(lines) == 2 and lines[1]["value"]:
        # The acceptance ratio: continuous over the legacy coalescer.
        lines[0]["vs_baseline"] = round(
            lines[0]["value"] / lines[1]["value"], 3
        )
    for line in lines:
        print(json.dumps(line), flush=True)
    return 0 if all(not line["errors"] for line in lines) else 1


if __name__ == "__main__":
    sys.exit(main())
