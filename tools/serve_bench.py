#!/usr/bin/env python
"""Mixed-length open-loop serving bench: continuous batching vs the
legacy batch-window coalescer, same model, same seeded traffic — plus
the long-context + shared-prefix CAPACITY mix: the paged KV cache vs
the dense slot tensor at the SAME byte budget.

The lm_decode bench line is a static-batch best case (one shape, lock
step, batch 8); THIS is the serving number: requests with ≥4 distinct
(prompt_len, num_steps) shapes arrive on a deterministic seeded open-loop
schedule (arrival times are data, independent of service rate — the
honest serving-load model), and each engine serves the identical
schedule. Both legs get one untimed dry run of the whole schedule first
(every executable warm), then the timed run measures steady-state
serving — so the comparison is engine mechanics (occupancy vs lock-step
coalescing), not compile luck.

Emits one BENCH-style JSON line per leg:

    {"metric": "serve_continuous_tokens_per_sec_mixed", "value": ...,
     "vs_baseline": <continuous / coalesce>, "ttft_p50_ms": ...,
     "ttft_p99_ms": ..., "itl_p50_ms": ..., "itl_p99_ms": ...,
     "mean_occupancy": ..., "steady_occupancy": ...}

vs_baseline on the continuous line is the speedup over the coalesce leg
(the acceptance ratio); ttft on the coalesce line is full-response
latency (lock-step clients see nothing earlier). itl_p50/p99 are
inter-token gaps pooled across requests — real decode-step gaps on the
continuous legs (ServeRequest.itl_values), latency/tokens on the
lock-step coalesce leg (nothing streams), the replica-reported timing
breakdown on the fleet leg. The pair (ttft_p99, itl_p99) is the
baseline the ROADMAP item-2 disaggregation pin must beat.
steady_occupancy is the mean active-slot fraction over the middle half
of decode steps — the window where admission has filled and drain has
not started.

The CAPACITY section (runs with ``--engine both``; ``--skip-prefix-mix``
disables) replays a seeded long-context + shared-prefix schedule — every
prompt opens with one common block-aligned system prefix, a fraction are
exact duplicates, and prompts use a small slice of a large max_seq_len —
through TWO continuous engines whose KV budgets are byte-identical: the
dense slot tensor (few max-len rows) and the paged block pool (same
bytes, 4x the slots). Each leg's line adds ``admitted_concurrency`` (the
slot high-water over the timed pass — what the byte budget actually
admitted), ``prefill_tokens_saved`` and ``cow_copies`` (prefix reuse at
work); the paged line's ``vs_baseline`` is its tokens/sec over the dense
leg and ``admitted_ratio`` the concurrency multiple — the ROADMAP item-2
"what fits at actual lengths" number. A third ``pallas_longctx`` leg
(ISSUE 18) replays the identical schedule, pool, and slot budget with
``kv_attend="pallas"`` — its ``vs_baseline`` is the kernel-vs-gather
ratio, with ``host_cpus`` stamped because a CPU round runs the kernel
in the pallas interpreter (mechanism proof only; hardware ratios come
from the next window).

The CHAOS mix (``--engine chaos``) replays the same seeded schedule
through a SUPERVISED continuous engine (serve/resilience.py) while the
seeded fault injector kills the decode step once and wedges it once
mid-run: the watchdog tears the engine down, rebuilds it, and replays
in-flight requests. The line pins the resilience claims — ``lost`` (a
request with no terminal outcome) must be 0, every request resolves as
ok / partial-with-flag / typed error, and ``ttft_p99_ms`` stays under
the deadline budget (``deadline_budget_ms``) — capacity-style
assertions enforced by the deadline machinery, not wall-clock luck.

The DISAGG pair (``--engine disagg``) is the ROADMAP item-2
interference mix: long prefills landing in a stream of
latency-sensitive short decodes, served once by the time-shared
supervised engine (chunked prefill budget-interleaved with decode —
the PR 5 mitigation at its best) and once DISAGGREGATED — the same
decode engine with every long prompt prefilled on a 2-replica prefill
pool and shipped as block-pool rows through the two-stage router, one
prefill replica KILLED mid-run. Both legs ride identical HTTP
plumbing and report engine-observed TTFT/ITL, so the delta is the
prefill PLACEMENT. The disagg line pins lost == 0 and
shipped_joins == the long-prompt count; its ``ttft_p99_vs_baseline``
/ ``itl_p99_vs_baseline`` ratios are the acceptance numbers — on
hosts where the prefill pool is real extra hardware (``host_cpus``
rides the line; CI's 1-core box shares one execution unit across all
"replicas", so its ratios invert and the line is a mechanism proof,
the tp pair's CPU story exactly).

The SPEC triple (``--engine spec``) is the ISSUE-15 acceptance run:
the identical seeded mixed-traffic schedule served by (1) the
continuous engine with BATCH-WIDE speculative decode (per-slot draft +
one batched verify per round, per-slot accept counters), (2) the plain
continuous engine, and (3) the legacy ``--spec-k`` path (lock-step
``speculative_generate`` behind the batch-window coalescer) — all on
one quick-trained target/draft pair (the +1-chain task, so the draft
genuinely accepts). The spec line's ``vs_baseline`` is
spec/continuous, ``vs_spec_coalesce`` its ratio over the legacy leg,
and ``accept_rate`` the timed pass's measured acceptance — the
acceptance pin is BOTH ratios > 1 while accept_rate stays realistic.

The TIER pair (``--engine tier``) is the ISSUE-17 acceptance run: a
seeded many-session RESUME mix (round-robin closed-loop turns, so
every session's retained prefix is reclaimed by the others' traffic
between its own turns) served twice at the IDENTICAL tight HBM block
budget — once with the host-RAM KV tier attached (evicted prefixes
spill to host and restore on resume, serve/tier.py) and once without
(evicted prefixes recompute). Greedy decoding, so the tier leg's
outputs must MATCH the recompute leg's token-for-token
(``outputs_match_baseline`` — the spill→restore bit-identity pin at
bench scale). The tier line's ``prefill_tokens_saved_vs_baseline``
(> 1: restores turn evictions back into joins) and
``resume_ttft_p50_vs_baseline`` (< 1 on hardware: restoring beats
recomputing; ``host_cpus`` rides the line for the CPU-round caveat)
are the acceptance numbers.

The TP pair (``--tp N``) replays the same schedule through the
continuous engine on an N-device ``tp`` mesh (SPMD decode: params
tp-sharded, KV storage head-sharded, one compiled step driving the
slice — the host-device trick supplies CPU devices, real chips on
hardware) and through the single-device engine as baseline; the tp
line's ``vs_baseline`` is tpN/tp1 and carries ``mesh_devices`` +
the zero-recompile pin. ``--tp N --dp M`` runs the POD-SCALE pair
instead (ISSUE 20): the engine on the 2-D tp x dp mesh (slot state and
the paged pool's block axis sharded over dp on top of the tp head
shard) vs the same tp at dp=1 on the identical schedule —
``vs_baseline`` = tpNdpM/tpNdp1, ``mesh_devices`` = N*M, same
zero-recompile pin; on CPU a mechanism proof, not a speedup.

All randomness is seeded (schedule, prompts); wall-clock only enters the
timing fields, so tests assert structure and token counts, never timing.
BENCH_SMOKE shrinks shapes for CI. Run:

    JAX_PLATFORMS=cpu python tools/serve_bench.py            # all legs
    python tools/serve_bench.py --engine continuous          # one leg
    python tools/serve_bench.py --engine chaos               # chaos mix
    python tools/serve_bench.py --tp 2                       # SPMD pair
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402

# (prompt_len, num_steps) mix — ≥4 shapes spanning short/long prompts and
# short/long horizons, so lock-step coalescing has real stragglers.
SHAPES = [(8, 24), (16, 48), (32, 16), (4, 64)]
SMOKE_SHAPES = [(4, 6), (8, 10), (12, 4), (2, 12)]

# Capacity-mix geometry: a large max_seq_len budget that every request
# uses only a small slice of (the dense layout's worst case), one common
# block-aligned prefix, short tails/horizons, a third exact duplicates.
CAPACITY = dict(seq=256, block=16, prefix=32, tails=(8, 16, 24, 32),
                steps=(8, 16), dense_slots=4, slot_mult=4, requests=32,
                gap_ms=3.0, exact_every=3)
# gap_ms 0: the smoke profile arrives ALL AT ONCE — CI asserts the
# admitted-concurrency ratio, and a guaranteed backlog makes that a
# capacity property rather than a wall-clock one (a machine fast enough
# to drain 2 ms open-loop arrivals would otherwise never queue).
SMOKE_CAPACITY = dict(seq=64, block=8, prefix=8, tails=(2, 4, 6),
                      steps=(4, 6), dense_slots=2, slot_mult=4,
                      requests=10, gap_ms=0.0, exact_every=3)

# Interference mix (ROADMAP item 2 / ISSUE 14): LONG prefills arriving
# into a stream of latency-sensitive short decodes — the TTFT/ITL
# tension disaggregation exists to remove. Every ``long_every``-th
# request is a ``long_prompt``-token prompt with a short horizon; the
# rest are short prompts with long horizons (their ITL is what the
# long prefills interfere with). Both legs serve the IDENTICAL seeded
# schedule: the time-shared leg runs one supervised continuous engine
# (chunked prefill budgeted at ``budget`` tokens per decode step — the
# PR 5 mitigation at its best), the disagg leg the same decode engine
# with prefill OFFLOADED to a 2-replica prefill pool through the
# two-stage router, one prefill replica KILLED mid-run.
# ship_min gates the hop to the LONG prompts only: short prompts
# prefill locally in one cheap slice — shipping them would just queue
# them behind the long prefills at the prefill pool and pay the wire
# for nothing (measured: ship-everything triples short-request TTFT).
INTERFERENCE = dict(seq=256, block=16, chunk=16, budget=32,
                    long_prompt=192, long_steps=8, long_every=5,
                    shapes=((8, 40), (16, 32), (4, 48)),
                    requests=40, gap_ms=10.0, ship_min=64)
SMOKE_INTERFERENCE = dict(seq=64, block=8, chunk=4, budget=8,
                          long_prompt=40, long_steps=4, long_every=4,
                          shapes=((4, 10), (6, 8), (2, 12)),
                          requests=16, gap_ms=8.0, ship_min=24)

# Multi-turn chat mix (ISSUE 16): ``sessions`` concurrent conversations
# of ``turns`` turns each; turn t's prompt is the WHOLE conversation so
# far (previous prompt + assistant tokens + ``user_tokens`` fresh user
# tokens), so consecutive turns share a growing block-aligned prefix —
# IF the router lands them on the replica that still holds the blocks.
# The prefix-aware leg routes with scoring + session affinity +
# retention; the baseline leg is the identical fleet behind the plain
# least-loaded router. Both replay the IDENTICAL seeded session set, so
# the delta is purely the ROUTING policy's prefix locality.
CHAT_MIX = dict(sessions=8, turns=4, user_tokens=16, steps=8,
                replicas=4, block=8, think_ms=20.0)
SMOKE_CHAT_MIX = dict(sessions=4, turns=3, user_tokens=8, steps=4,
                      replicas=2, block=8, think_ms=0.0)
# The tier resume mix: enough sessions that the tight block pool
# evicts each idle session's retained prefix before its next turn
# (pool_extra blocks of headroom over ONE conversation's worst case).
TIER_MIX = dict(sessions=6, turns=3, user_tokens=24, steps=8,
                block=8, pool_extra=4, retain=64)
SMOKE_TIER_MIX = dict(sessions=3, turns=2, user_tokens=16, steps=4,
                      block=8, pool_extra=4, retain=64)


def build_schedule(n_requests: int, mean_gap_ms: float, seed: int,
                   shapes, vocab: int):
    """Deterministic open-loop traffic: [(t_offset_s, prompt, steps)]."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for _ in range(n_requests):
        p, steps = shapes[int(rng.integers(0, len(shapes)))]
        prompt = rng.integers(0, vocab, (1, p)).astype(np.int32)
        out.append((t, prompt, steps))
        t += float(rng.exponential(mean_gap_ms)) / 1e3
    return out


def run_schedule(schedule, submit_fn):
    """Replay the schedule open-loop (one client thread per request,
    sleeping to its arrival time). Returns (wall_seconds, results):
    results[i] = dict(tokens, latency_s, ttft_s | None, itls,
    error | None) — ``itls`` is the request's inter-token gap list
    (submit_fn's third return value; empty for legs that cannot
    measure per-token delivery)."""
    results = [None] * len(schedule)
    start = time.perf_counter() + 0.05  # common epoch for all arrivals

    def client(i, offset, prompt, steps):
        delay = start + offset - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t0 = time.perf_counter()
        try:
            tokens, ttft, itls = submit_fn(prompt, steps)
            results[i] = {
                "tokens": tokens,
                "latency_s": time.perf_counter() - t0,
                "ttft_s": ttft if ttft is not None
                else time.perf_counter() - t0,
                "itls": itls or [],
                "error": None,
            }
        except Exception as exc:  # noqa: BLE001 — one failed request
            # must not hang the bench join below.
            results[i] = {"tokens": None, "latency_s": 0.0,
                          "ttft_s": 0.0, "itls": [], "error": repr(exc)}

    threads = [
        threading.Thread(target=client, args=(i, off, prompt, steps))
        for i, (off, prompt, steps) in enumerate(schedule)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600.0)
    return time.perf_counter() - t0, results


def percentile(values, q):
    if not values:
        return 0.0
    vals = sorted(values)
    return vals[min(len(vals) - 1, int(q * len(vals)))]


def leg_summary(name, wall_s, results, extra):
    errors = [r["error"] for r in results if r and r["error"]]
    tokens = sum(len(r["tokens"]) for r in results if r and r["tokens"]
                 is not None)
    ttfts = [r["ttft_s"] for r in results if r and r["error"] is None]
    lats = [r["latency_s"] for r in results if r and r["error"] is None]
    # Inter-token gaps pooled across requests: the ROADMAP item-2
    # interference pin's baseline (disaggregation must beat BOTH TTFT
    # p99 and ITL p99 of the time-shared engine).
    itls = [g for r in results if r and r["error"] is None
            for g in r.get("itls", ())]
    line = {
        "metric": f"serve_{name}_tokens_per_sec_mixed",
        "value": round(tokens / wall_s, 1) if wall_s else 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "requests": len(results),
        "errors": len(errors),
        "generated_tokens": tokens,
        "wall_seconds": round(wall_s, 3),
        "ttft_p50_ms": round(percentile(ttfts, 0.5) * 1e3, 1),
        "ttft_p99_ms": round(percentile(ttfts, 0.99) * 1e3, 1),
        "itl_p50_ms": round(percentile(itls, 0.5) * 1e3, 2),
        "itl_p99_ms": round(percentile(itls, 0.99) * 1e3, 2),
        "latency_p50_ms": round(percentile(lats, 0.5) * 1e3, 1),
        "latency_p99_ms": round(percentile(lats, 0.99) * 1e3, 1),
    }
    line.update(extra)
    if errors:
        line["first_error"] = errors[0]
    return line


def run_continuous(cfg, params, schedule, args, *, mesh=None,
                   name="continuous", spec_k=0, draft_cfg=None,
                   draft_params=None) -> dict:
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.scheduler import (
        ContinuousScheduler,
        ServeRequest,
    )

    # ONE engine for both passes: the dry run warms ITS jit caches (the
    # whole point — a fresh engine would recompile on the clock and the
    # line would measure compiles, not serving).
    engine = ContinuousEngine(
        cfg, params, max_slots=args.max_batch,
        prefill_chunk=args.prefill_chunk or None,
        mesh=mesh, spec_k=spec_k, draft_cfg=draft_cfg,
        draft_params=draft_params,
    )
    sched = ContinuousScheduler(
        engine, prefill_tokens_per_step=args.prefill_budget
    ).start()

    def submit(prompt, steps):
        req = sched.submit_request(ServeRequest(prompt, steps))
        return list(req.out), req.ttft, req.itl_values()

    run_schedule(schedule, submit)  # untimed warmup
    sched.reset_stats()
    spec0 = engine.spec_debug() if spec_k else None
    wall_s, results = run_schedule(schedule, submit)
    steady = list(sched.step_log)
    mid = steady[len(steady) // 4: max(len(steady) // 4 + 1,
                                       3 * len(steady) // 4)]
    stats = {
        "mean_occupancy": round(sched.mean_occupancy, 3),
        "steady_occupancy": round(
            sum(mid) / len(mid) / engine.max_slots, 3
        ) if mid else 0.0,
        "decode_steps": sched.decode_steps,
        "decode_step_compiles": engine.decode_step_compiles,
        "warmup_compiles": engine.warmup_compiles,
        "max_batch": engine.max_slots,
        "prefill_chunk": args.prefill_chunk or None,
        "mesh_devices": engine.mesh_info()["devices"],
    }
    if spec_k:
        # Accept rate over the TIMED pass only (the warmup pass served
        # the identical schedule, so the deltas are the window's).
        spec1 = engine.spec_debug()
        lanes = spec1["lane_rounds"] - spec0["lane_rounds"]
        toks = spec1["tokens"] - spec0["tokens"]
        tpr = toks / lanes if lanes else 0.0
        stats.update({
            "spec_k": spec_k,
            "spec_rounds": spec1["rounds"] - spec0["rounds"],
            "accept_rate": round(max(0.0, tpr - 1.0) / spec_k, 4),
            "tokens_per_lane_round": round(tpr, 3),
        })
    sched.stop(timeout=30.0)
    return leg_summary(name, wall_s, results, stats)


def run_tp_legs(cfg, params, schedule, args) -> list[dict]:
    """The SPMD tensor-parallel pair: the continuous engine on a
    ``--tp``-device mesh (params tp-sharded by the training rules, KV
    storage head-sharded, ONE compiled step driving every device) and
    the single-device engine on the IDENTICAL schedule as its baseline.
    The tp line's vs_baseline is tpN/tp1 tokens/sec. On CPU host
    devices this measures the mechanism, not a speedup — the per-step
    collectives cost real time against zero extra memory bandwidth; the
    line exists so hardware rounds report the true slice number through
    the same plumbing and so the structural pins (zero recompiles,
    mesh>1 in the line) hold everywhere."""
    import jax

    from tf_operator_tpu.parallel.mesh import create_mesh

    if len(jax.devices()) < args.tp:
        raise SystemExit(
            f"serve_bench: --tp {args.tp} needs {args.tp} devices, "
            f"have {len(jax.devices())}"
        )
    mesh = create_mesh({"tp": args.tp}, jax.devices()[: args.tp])
    tp_line = run_continuous(cfg, params, schedule, args, mesh=mesh,
                             name=f"tp{args.tp}")
    base = run_continuous(cfg, params, schedule, args, name="tp1")
    if base["value"]:
        tp_line["vs_baseline"] = round(tp_line["value"] / base["value"],
                                       3)
    return [tp_line, base]


def run_tpdp_legs(cfg, params, schedule, args) -> list[dict]:
    """The pod-scale pair (ISSUE 20): the continuous engine on the 2-D
    ``tp x dp`` mesh — per-slot state and the paged pool's block axis
    sharded over dp on top of the tp head shard, ONE compiled step
    driving every device — vs the SAME tp width at dp=1 on the
    IDENTICAL seeded schedule. The tpdp line's vs_baseline is
    tp{N}dp{M}/tp{N}dp1 tokens/sec and carries ``mesh_devices`` (=N*M)
    plus the zero-recompile pin (``decode_step_compiles`` ==
    ``warmup_compiles``). On CPU host devices this is a MECHANISM
    proof, not a speedup — dp buys aggregate slots/HBM only on real
    chips; the line exists so hardware rounds report the true pod
    number through the same plumbing."""
    import jax

    from tf_operator_tpu.parallel.mesh import create_mesh

    need = args.tp * args.dp
    if len(jax.devices()) < need:
        raise SystemExit(
            f"serve_bench: --tp {args.tp} --dp {args.dp} needs {need} "
            f"devices, have {len(jax.devices())}"
        )
    if args.max_batch % args.dp:
        raise SystemExit(
            f"serve_bench: --dp {args.dp} must divide --max-batch "
            f"{args.max_batch} (each dp shard owns an equal slot slice)"
        )
    mesh2 = create_mesh({"tp": args.tp, "dp": args.dp},
                        jax.devices()[:need])
    line = run_continuous(cfg, params, schedule, args, mesh=mesh2,
                          name=f"tp{args.tp}dp{args.dp}")
    mesh1 = create_mesh({"tp": args.tp}, jax.devices()[: args.tp])
    base = run_continuous(cfg, params, schedule, args, mesh=mesh1,
                          name=f"tp{args.tp}dp1")
    if base["value"]:
        line["vs_baseline"] = round(line["value"] / base["value"], 3)
    return [line, base]


# Constrained-decoding mix (ISSUE 19): every ``every``-th request
# carries a bounded JSON-schema grammar (string maxLength + boolean —
# every DFA path is finite, so completion is GUARANTEED inside the
# step budget, and grammar_valid == constrained_requests is a hard pin,
# not a coin flip). Both legs serve the IDENTICAL seeded schedule; the
# free leg drops the grammar, so the mixed line's vs_baseline is purely
# the mask-gather + host-walk overhead (the acceptance bound: bounded,
# near-1 — the mask is data, not a recompile).
CONSTRAIN_MIX = dict(requests=24, gap_ms=4.0,
                     shapes=((6, 40), (10, 40), (4, 48)), every=2)
SMOKE_CONSTRAIN_MIX = dict(requests=10, gap_ms=2.0,
                           shapes=((4, 32), (6, 32)), every=2)


def run_constrain_legs(cfg, params, args, smoke: bool) -> list[dict]:
    """The ISSUE-19 acceptance pair: the continuous engine serving the
    identical seeded schedule FREE (baseline) and MIXED (every other
    request under a compiled JSON-schema grammar program). Capacity
    pins, no wall-clock: every constrained request retires
    grammar_complete with output that actually parses
    (grammar_valid == constrained_requests), the free leg's streams are
    untouched by the mask plumbing, and BOTH legs hold the
    zero-recompile pin across the constrained/free occupancy churn."""
    import json as _json

    from tf_operator_tpu.serve.constrain import (
        ConstraintCompiler,
        default_vocab,
        detokenize,
    )
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.scheduler import (
        ContinuousScheduler,
        ServeRequest,
    )

    mix = SMOKE_CONSTRAIN_MIX if smoke else CONSTRAIN_MIX
    schedule = build_schedule(mix["requests"], mix["gap_ms"], args.seed,
                              mix["shapes"], cfg.vocab_size)
    if cfg.vocab_size >= 128:
        # chr-identity vocab covers ASCII: the real JSON-schema path.
        spec = {"json_schema": {
            "type": "object",
            "properties": {"name": {"type": "string", "maxLength": 4},
                           "ok": {"type": "boolean"}},
            "required": ["name", "ok"],
        }}
        valid = lambda s: isinstance(_json.loads(s), dict)  # noqa: E731
    else:
        # tiny --vocab: digits still tokenize; same bounded-DFA pin.
        spec = {"regex": "[0-9]{2,8}"}
        valid = lambda s: s.isdigit() and 2 <= len(s) <= 8  # noqa: E731
    constrainer = ConstraintCompiler(default_vocab(cfg.vocab_size))
    vocab = default_vocab(cfg.vocab_size)
    lines = []
    for name, constrained in (("constrain_free", False),
                              ("constrain_mixed", True)):
        engine = ContinuousEngine(
            cfg, params, max_slots=args.max_batch,
            prefill_chunk=args.prefill_chunk or None,
            constrain_rows=64,
        )
        sched = ContinuousScheduler(
            engine, constrainer=constrainer,
            prefill_tokens_per_step=args.prefill_budget,
        ).start()
        spec_by_key = {
            prompt.tobytes(): (spec if constrained and i % mix["every"]
                               else None)
            for i, (_, prompt, _s) in enumerate(schedule)
        }
        done = []
        done_lock = threading.Lock()

        def submit(prompt, steps):
            req = sched.submit_request(ServeRequest(
                prompt, steps, constrain=spec_by_key[prompt.tobytes()]
            ))
            with done_lock:
                done.append(req)
            return list(req.out), req.ttft, req.itl_values()

        run_schedule(schedule, submit)  # untimed warmup
        done.clear()
        sched.reset_stats()
        wall_s, results = run_schedule(schedule, submit)
        con = [r for r in done if r.constrain is not None]
        grammar_valid = sum(
            1 for r in con
            if r.finish_reason == "grammar_complete"
            and valid(detokenize(vocab, r.out))
        )
        dbg = engine.constrain_debug()
        stats = {
            "constrained_requests": len(con),
            "grammar_valid": grammar_valid,
            "grammar_complete": sum(
                1 for r in con
                if r.finish_reason == "grammar_complete"
            ),
            "constrain_programs": dbg["programs"],
            "constrain_rows_used": dbg["rows_used"],
            "decode_steps": sched.decode_steps,
            "decode_step_compiles": engine.decode_step_compiles,
            "warmup_compiles": engine.warmup_compiles,
            "max_batch": engine.max_slots,
        }
        sched.stop(timeout=30.0)
        lines.append(leg_summary(name, wall_s, results, stats))
    # Treatment first (the pair convention main's ratio block keys on):
    # the mixed line's vs_baseline becomes mixed/free — the bounded
    # mask-gather + host-walk overhead on the identical schedule.
    return [lines[1], lines[0]]


def train_lm_params(cfg, steps: int, lr: float, seq: int, seed: int = 0):
    """Train the +1-mod-vocab chain task (serve_lm's quick_train,
    batch 16 over full-length chains) — the SPEC legs need a draft
    that genuinely agrees with the target: random params would pin
    acceptance at ~0 and the leg would measure nothing but overhead.
    Training covers every position the schedule decodes (``seq``), so
    acceptance stays high across the whole horizon (measured: loss
    ~1e-3 and ~0.95 acceptance at these shapes/steps)."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import Transformer
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.train.steps import (
        TrainState,
        adamw,
        make_lm_train_step,
    )

    mesh = create_mesh({"dp": 1}, jax.devices()[:1])
    model = Transformer(cfg)
    rng = np.random.default_rng(seed)
    start = rng.integers(0, cfg.vocab_size, (16, 1))
    seq = min(seq, cfg.max_seq_len - 1)
    chain = (start + np.arange(seq + 1)) % cfg.vocab_size
    batch = {
        "tokens": jnp.asarray(chain[:, :-1], jnp.int32),
        "targets": jnp.asarray(chain[:, 1:], jnp.int32),
    }
    params = model.init(jax.random.PRNGKey(0), batch["tokens"])["params"]
    tx = adamw(lr)
    state = TrainState.create(params, tx)
    step = make_lm_train_step(model, tx, mesh, seq_axis=None,
                              donate=False)
    for _ in range(steps):
        state, _ = step(state, batch)
    return state.params


# Spec-mix shapes: mixed prompts with DECODE-heavy horizons — the
# regime speculation accelerates (short horizons spend their rounds on
# the trimmed overshoot; the main mix's 4-12-step requests would
# quantize tokens/round down regardless of acceptance).
SPEC_SHAPES = [(8, 24), (16, 32), (4, 40), (12, 16)]
SMOKE_SPEC_SHAPES = [(4, 12), (8, 16), (2, 20), (6, 10)]


def run_spec_legs(cfg, schedule, args, smoke: bool,
                  mesh=None) -> list[dict]:
    """The ISSUE-15 acceptance triple on ONE seeded schedule and ONE
    trained target: batch-wide speculative continuous engine vs the
    plain continuous engine vs the legacy --spec-k coalesce path
    (lock-step ``speculative_generate`` behind the batch window). The
    spec line's ``vs_baseline`` is spec/continuous and
    ``vs_spec_coalesce`` its ratio over the legacy leg — BOTH must
    exceed 1.0 for the acceptance pin — with the timed pass's
    ``accept_rate`` riding the line (a draft that stopped accepting
    turns the comparison meaningless, so the structural test checks
    it first). Target/draft are quick-trained on the +1-chain task
    (serve_lm's own demo task): after a random prompt's first token
    the continuation is deterministic, so a trained draft accepts at
    a realistic high rate while remaining a genuinely smaller model."""
    from tf_operator_tpu.models.spec_decode import (
        spec_margin,
        speculative_generate,
    )
    from tf_operator_tpu.models.transformer import TransformerConfig

    k = args.spec_k
    shapes = SMOKE_SPEC_SHAPES if smoke else SPEC_SHAPES
    schedule = build_schedule(len(schedule), args.mean_gap_ms,
                              args.seed, shapes, 64)
    horizon = max(p.shape[1] + s for _, p, s in schedule)
    if horizon + spec_margin(k) > cfg.max_seq_len:
        raise SystemExit(
            f"serve_bench: spec-mix horizon {horizon} + margin "
            f"{spec_margin(k)} exceeds max_seq_len {cfg.max_seq_len}"
        )
    # The leg's own geometry (like the capacity/interference mixes):
    # vocab 64 x d_model 64 is the smallest pair the +1-chain task
    # trains to near-exact continuation on in seconds — the bench
    # cfg's vocab-128 x d-32 quick-train does NOT converge, and an
    # unconverged pair pins acceptance at ~0, measuring nothing but
    # speculation overhead.
    import jax.numpy as jnp

    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4,
        n_layers=cfg.n_layers, d_ff=128,
        max_seq_len=cfg.max_seq_len, dtype=jnp.float32,
    )
    # The draft earns its keep by being CHEAP: one layer at a quarter
    # of the target's width still drafts the chain task at ~0.9
    # acceptance (measured 4.46 tokens/round at k=4), and its per-token
    # cost is ~1/8 of the target's — the realistic draft/target cost
    # ratio the speedup model assumes.
    draft_cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2,
        n_layers=1, d_ff=64,
        max_seq_len=cfg.max_seq_len, dtype=jnp.float32,
    )
    train_steps = 200 if smoke else 300
    params = train_lm_params(cfg, train_steps, 5e-3,
                             horizon + spec_margin(k))
    draft_params = train_lm_params(draft_cfg, train_steps, 5e-3,
                                   horizon + spec_margin(k), seed=1)

    # Same arrival times and shapes as the shared schedule, but the
    # prompt CONTENT is +1-chains from seeded random starts — the
    # distribution the pair was trained on. Random-token prompts are
    # out-of-distribution noise to a quick-trained model: target and
    # draft then disagree on noise and acceptance pins near zero,
    # measuring nothing but overhead. Speculation's win IS predictable
    # continuations (the production argument for a trained draft), so
    # the leg serves the workload that has them; all three legs serve
    # this IDENTICAL schedule.
    rng = np.random.default_rng(args.seed + 17)
    schedule = [
        (t, ((int(rng.integers(0, cfg.vocab_size))
              + np.arange(prompt.shape[1])) % cfg.vocab_size
             ).astype(np.int32)[None], steps)
        for t, prompt, steps in schedule
    ]

    if mesh is not None:
        # tp>1 triple: BOTH continuous legs ride the mesh (the engine
        # shards target + draft by the training rules), and the legacy
        # leg's solo speculative_generate runs on the same tp-sharded
        # params via GSPMD — the identical-model contract holds at
        # every width.
        from tf_operator_tpu.models.transformer import (
            param_sharding_rules,
        )
        from tf_operator_tpu.parallel.sharding import (
            shard_params_by_rules,
        )

        params = shard_params_by_rules(mesh, params,
                                       param_sharding_rules())
        draft_params = shard_params_by_rules(mesh, draft_params,
                                             param_sharding_rules())
    spec_line = run_continuous(
        cfg, params, schedule, args, name="spec", spec_k=k,
        draft_cfg=draft_cfg, draft_params=draft_params, mesh=mesh,
    )
    cont_line = run_continuous(cfg, params, schedule, args,
                               name="continuous", mesh=mesh)

    def spec_decode(rows, num_steps):
        out, _ = speculative_generate(
            cfg, params, draft_cfg, draft_params, rows, num_steps, k=k,
        )
        return out

    legacy_line = run_coalesce(cfg, params, schedule, args,
                               decode_fn=spec_decode,
                               name="spec_coalesce")
    if cont_line["value"]:
        spec_line["vs_baseline"] = round(
            spec_line["value"] / cont_line["value"], 3
        )
    if legacy_line["value"]:
        spec_line["vs_spec_coalesce"] = round(
            spec_line["value"] / legacy_line["value"], 3
        )
    return [spec_line, cont_line, legacy_line]


def build_prefix_schedule(cap: dict, seed: int, vocab: int):
    """Deterministic long-context + shared-prefix traffic: every prompt
    opens with ONE common block-aligned prefix, tails/horizons vary, and
    every ``exact_every``-th request replays an earlier prompt verbatim
    (the exact-match/CoW path)."""
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, vocab, (cap["prefix"],)).astype(np.int32)
    out, prompts, t = [], [], 0.0
    for i in range(cap["requests"]):
        if prompts and i % cap["exact_every"] == 0:
            prompt = prompts[int(rng.integers(0, len(prompts)))]
        else:
            tail = rng.integers(
                0, vocab, (int(rng.choice(cap["tails"])),)
            ).astype(np.int32)
            prompt = np.concatenate([prefix, tail])[None]
            prompts.append(prompt)
        out.append((t, prompt, int(rng.choice(cap["steps"]))))
        t += float(rng.exponential(cap["gap_ms"])) / 1e3
    return out


def run_capacity_leg(name, cfg, params, schedule, args, *, kv_paged,
                     max_slots, kv_blocks, kv_block,
                     kv_attend="gather") -> dict:
    """One capacity-mix leg: a continuous engine (paged or dense) under
    the shared-prefix long-context schedule, admitted concurrency and
    prefix-reuse counters measured over the timed pass only."""
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.scheduler import (
        ContinuousScheduler,
        ServeRequest,
    )

    engine = ContinuousEngine(
        cfg, params, max_slots=max_slots,
        prefill_chunk=args.prefill_chunk or None,
        kv_paged=kv_paged, kv_block=kv_block, kv_blocks=kv_blocks,
        kv_attend=kv_attend,
    )
    sched = ContinuousScheduler(
        engine, prefill_tokens_per_step=args.prefill_budget
    ).start()

    def submit(prompt, steps):
        req = sched.submit_request(ServeRequest(prompt, steps))
        return list(req.out), req.ttft, req.itl_values()

    run_schedule(schedule, submit)  # untimed warmup (same engine)
    sched.reset_stats()
    engine.alloc.reset_high_water()
    saved0 = getattr(engine, "prefill_tokens_saved", 0)
    cows0 = getattr(engine, "cow_copies", 0)
    wall_s, results = run_schedule(schedule, submit)
    stats = {
        "kv": "paged" if kv_paged else "dense",
        "kv_attend": kv_attend if kv_paged else None,
        "admitted_concurrency": engine.alloc.high_water,
        "prefill_tokens_saved":
            getattr(engine, "prefill_tokens_saved", 0) - saved0,
        "cow_copies": getattr(engine, "cow_copies", 0) - cows0,
        "max_batch": max_slots,
        "kv_block": kv_block if kv_paged else None,
        "kv_blocks": engine.kv_blocks,
        "max_seq_len": cfg.max_seq_len,
        "decode_step_compiles": engine.decode_step_compiles,
        "warmup_compiles": engine.warmup_compiles,
    }
    sched.stop(timeout=30.0)
    return leg_summary(name, wall_s, results, stats)


def run_capacity_mix(args, smoke: bool) -> list[dict]:
    """The paged-vs-dense capacity comparison at ONE byte budget: the
    dense leg gets ``dense_slots`` max-len rows; the paged leg gets the
    SAME bytes as a block pool (dense_slots x table_len blocks + the
    pinned garbage block) but ``slot_mult`` x the slots — whether that
    budget admits more live long-context requests is exactly the
    paged-cache claim."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )

    cap = SMOKE_CAPACITY if smoke else CAPACITY
    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=4,
        n_layers=args.layers, d_ff=args.d_model * 2,
        max_seq_len=cap["seq"], dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    schedule = build_prefix_schedule(cap, args.seed, args.vocab)
    table_len = cap["seq"] // cap["block"]
    pool = cap["dense_slots"] * table_len + 1  # the dense byte budget
    paged = run_capacity_leg(
        "paged_longctx", cfg, params, schedule, args, kv_paged=True,
        max_slots=cap["dense_slots"] * cap["slot_mult"],
        kv_blocks=pool, kv_block=cap["block"],
    )
    # The ISSUE 18 kernel A/B: the SAME seeded schedule, pool, and slot
    # budget with the pallas paged-attend instead of the gather read —
    # the capacity story is identical (admission is an allocator
    # property), the per-step attend cost is the variable. host_cpus
    # rides the line: on a CPU round the kernel runs in the pallas
    # INTERPRETER, so the ratio is mechanism proof only — real numbers
    # come from the next hardware window (probe_kvblock + this leg).
    import os as _os

    pallas = run_capacity_leg(
        "pallas_longctx", cfg, params, schedule, args, kv_paged=True,
        max_slots=cap["dense_slots"] * cap["slot_mult"],
        kv_blocks=pool, kv_block=cap["block"], kv_attend="pallas",
    )
    pallas["host_cpus"] = _os.cpu_count()
    dense = run_capacity_leg(
        "dense_longctx", cfg, params, schedule, args, kv_paged=False,
        max_slots=cap["dense_slots"], kv_blocks=None,
        kv_block=cap["block"],
    )
    if dense["value"]:
        paged["vs_baseline"] = round(paged["value"] / dense["value"], 3)
    if paged["value"]:
        # pallas vs gather on the identical schedule: the kernel ratio.
        pallas["vs_baseline"] = round(
            pallas["value"] / paged["value"], 3)
    if dense["admitted_concurrency"]:
        paged["admitted_ratio"] = round(
            paged["admitted_concurrency"]
            / dense["admitted_concurrency"], 3
        )
    return [paged, pallas, dense]


def run_chaos_leg(cfg, params, schedule, args) -> dict:
    """The seeded chaos mix: the open-loop schedule against a supervised
    engine while the injector crashes the step once and stalls it once
    mid-run. Zero lost requests and deadline-bounded TTFT are the
    assertions; tokens/sec under failure is the informational value."""
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.faultinject import FaultInjector
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
        ServeError,
    )
    from tf_operator_tpu.serve.scheduler import ServeRequest

    inj = FaultInjector(seed=args.seed)

    def factory():
        return ContinuousEngine(
            cfg, params, max_slots=args.max_batch,
            prefill_chunk=args.prefill_chunk or None, faults=inj,
        )

    res = ResilienceConfig(
        queue_ttl_s=30.0, decode_deadline_s=60.0, watchdog_stall_s=5.0,
        max_restarts=5, restart_backoff_s=0.1,
        queue_limit=max(64, 4 * len(schedule)),
    )
    sup = EngineSupervisor(
        factory, resilience=res, faults=inj,
        prefill_tokens_per_step=args.prefill_budget,
    )
    reqs: list = []

    def submit(prompt, steps):
        r = ServeRequest(prompt, steps)
        reqs.append(r)  # list.append is atomic; order is irrelevant
        r = sup.submit_request(r, timeout=120.0)
        return list(r.out), r.ttft, r.itl_values()

    run_schedule(schedule, submit)  # untimed warmup, no faults armed
    reqs.clear()
    sup.scheduler.reset_stats()
    restarts0 = sup.restarts
    # Seeded fault positions relative to the warmed counters: one crash
    # ~early, one wedge ~mid-run (the stall must out-wait the watchdog).
    total_steps = sum(s for _, _, s in schedule)
    inj.arm(f"step_raise@{inj.invocations['step_raise'] + max(2, total_steps // (4 * args.max_batch))}")
    inj.arm(f"step_stall@{inj.invocations['step_stall'] + max(4, total_steps // (2 * args.max_batch))}:8.0")
    wall_s, results = run_schedule(schedule, submit)
    inj.disarm()
    lost = sum(1 for r in reqs if not r.event.is_set())
    ok = sum(1 for r in reqs
             if r.error is None and not r.deadline_exceeded)
    partial = sum(1 for r in reqs if r.deadline_exceeded)
    typed = sum(1 for r in reqs if isinstance(r.error, ServeError))
    untyped = sum(1 for r in reqs
                  if r.error is not None
                  and not isinstance(r.error, ServeError))
    stats = {
        "resolved": len(reqs) - lost,
        "lost": lost,
        "ok": ok,
        "deadline_partials": partial,
        "typed_errors": typed,
        "untyped_errors": untyped,
        "watchdog_restarts": sup.restarts - restarts0,
        "replica_dead": sup.dead,
        "deadline_budget_ms": round(res.decode_deadline_s * 1e3, 1),
        "max_batch": args.max_batch,
        "faults": {k: v for k, v in inj.fired.items() if v},
    }
    sup.stop(timeout=30.0)
    line = leg_summary("chaos", wall_s, results, stats)
    # The chaos line's error count reflects TYPED resolutions (they are
    # the contract, not failures of the bench leg itself) — the exit
    # code keys off lost/untyped instead.
    line["errors"] = untyped + lost
    return line


def run_fleet_leg(cfg, params, schedule, args) -> dict:
    """The fleet e2e: the open-loop schedule through the fleet ROUTER
    over ``--fleet-replicas`` supervised continuous engines (each behind
    its own in-process HTTP replica, fleet/replica.py), with one replica
    KILLED mid-run. Zero lost requests (ok + partial + typed == total)
    and deadline-bounded TTFT are the assertions — the router's
    transport failover and typed-retry policy are what absorb the kill;
    tokens/sec through the router is the informational value."""
    from tf_operator_tpu.fleet.membership import FleetMembership, Replica
    from tf_operator_tpu.fleet.replica import (
        ReplicaServer,
        SupervisorBackend,
    )
    from tf_operator_tpu.fleet.router import (
        RouterConfig,
        RouterServer,
        http_probe,
        http_send,
    )
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
    )

    n = args.fleet_replicas
    res = ResilienceConfig(
        queue_ttl_s=30.0, decode_deadline_s=60.0, watchdog_stall_s=5.0,
        max_restarts=3, restart_backoff_s=0.1,
        queue_limit=max(64, 4 * len(schedule)),
    )

    def mk_replica(i: int) -> tuple[EngineSupervisor, ReplicaServer]:
        sup = EngineSupervisor(
            lambda: ContinuousEngine(
                cfg, params, max_slots=args.max_batch,
                prefill_chunk=args.prefill_chunk or None,
            ),
            resilience=res,
            prefill_tokens_per_step=args.prefill_budget,
        )
        server = ReplicaServer(
            SupervisorBackend(sup, request_timeout_s=90.0),
            replica_id=f"bench-r{i}",
        ).start()
        return sup, server

    replicas = [mk_replica(i) for i in range(n)]
    ms = FleetMembership(fail_threshold=2)
    for _, server in replicas:
        ms.register(server.replica_id, server.endpoint)
    router = RouterServer(
        ms, config=RouterConfig(retries=2, request_timeout_s=90.0,
                                probe_interval_s=0.1),
    ).start()
    ms.probe(http_probe)  # promote everyone before the first arrival

    outcomes: list = []
    outcomes_lock = threading.Lock()

    # The router's own transport (typed-error bodies come back as
    # (status, payload), only transport failures raise) pointed AT the
    # router — one wire-contract implementation, not a bench copy.
    router_as_backend = Replica(id="router", endpoint=router.endpoint)

    def submit(prompt, steps):
        try:
            status, payload = http_send(
                router_as_backend,
                # timing: the replica-side compact breakdown rides the
                # response, so the fleet leg's ITL comes from the
                # replica's own decode-step stamps, not router-side
                # guesswork.
                {"tokens": prompt.tolist(), "num_steps": steps,
                 "timing": True},
                90.0,
            )
        except Exception:  # noqa: BLE001 — transport to the ROUTER
            # itself failed: untyped, counted against the leg.
            with outcomes_lock:
                outcomes.append((None, {}))
            raise
        with outcomes_lock:
            outcomes.append((status, payload))
        if status == 200 and payload.get("tokens"):
            timing = (payload.get("timing") or [{}])[0]
            # The raw per-request gap list: pooled across requests this
            # leg's itl_p99 means the same thing as the in-process
            # legs' (a p99 of gaps, not a p99 of per-request means).
            gaps = [g / 1e3 for g in timing.get("itl_ms", ())]
            return payload["tokens"][0], None, gaps
        raise RuntimeError(f"{status}:{payload.get('code', 'untyped')}")

    run_schedule(schedule, submit)  # untimed warmup, whole fleet alive
    outcomes.clear()

    # Kill one replica as the mid-run arrivals land: its in-flight
    # requests die with the socket and MUST resolve via router failover.
    kill_at = schedule[len(schedule) // 2][0]
    victim_sup, victim_server = replicas[0]
    killer = threading.Timer(max(0.05, kill_at), victim_server.kill)
    killer.start()
    wall_s, results = run_schedule(schedule, submit)
    killer.cancel()  # no-op when it fired; cleanup when it never did

    ok = sum(1 for s, p in outcomes
             if s == 200 and not p.get("deadline_exceeded"))
    partial = sum(1 for s, p in outcomes
                  if s == 200 and p.get("deadline_exceeded"))
    typed = sum(1 for s, p in outcomes
                if s is not None and s >= 400 and p.get("code"))
    untyped = sum(1 for s, p in outcomes
                  if s is None or (s >= 400 and not p.get("code")))
    lost = len(schedule) - len(outcomes)
    rsnap = router.router.snapshot()
    stats = {
        "resolved": len(outcomes),
        "lost": lost,
        "ok": ok,
        "deadline_partials": partial,
        "typed_errors": typed,
        "untyped_errors": untyped,
        "replicas": n,
        "killed_replicas": 1,
        "router_retries": rsnap["retries"],
        "router_failovers": rsnap["failovers"],
        "membership": ms.counts(),
        "deadline_budget_ms": round(res.decode_deadline_s * 1e3, 1),
        "max_batch": args.max_batch,
    }
    router.stop()
    for sup, server in replicas:
        if server is not victim_server:
            server.stop()
        sup.stop(timeout=30.0)
    line = leg_summary("fleet", wall_s, results, stats)
    # Typed resolutions are the contract, not bench failures — the exit
    # code keys off lost/untyped, as in the chaos leg.
    line["errors"] = untyped + lost
    return line


def build_chat_sessions(mix: dict, seed: int, vocab: int):
    """Seeded multi-turn conversations: [(session_id, [user_turn, ...],
    steps)] — each user_turn a fresh [user_tokens] int32 chunk the
    runner appends to the conversation before resubmitting it whole."""
    rng = np.random.default_rng(seed)
    out = []
    for s in range(mix["sessions"]):
        turns = [
            rng.integers(0, vocab, (mix["user_tokens"],)).astype(np.int32)
            for _ in range(mix["turns"])
        ]
        out.append((f"chat-{s}", turns, mix["steps"]))
    return out


def _run_chat_leg(name, cfg, params, sessions, mix, args, *,
                  prefix_aware: bool) -> dict:
    """One chat leg: ``mix['replicas']`` supervised paged continuous
    engines (prefix retention ON — the engine side is identical on both
    legs) behind the fleet router; ``prefix_aware`` selects the routing
    policy under test (prefix-hit-weighted scoring + session affinity +
    cross-replica pulls) vs the plain least-loaded baseline. Sessions
    run closed-loop (turn t+1 waits for turn t — a conversation), all
    sessions concurrently."""
    from tf_operator_tpu.fleet.membership import FleetMembership, Replica
    from tf_operator_tpu.fleet.prefixes import PrefixConfig
    from tf_operator_tpu.fleet.replica import (
        ReplicaServer,
        SupervisorBackend,
    )
    from tf_operator_tpu.fleet.router import (
        RouterConfig,
        RouterServer,
        http_probe,
        http_send,
    )
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
    )

    n = mix["replicas"]
    res = ResilienceConfig(
        queue_ttl_s=30.0, decode_deadline_s=60.0, watchdog_stall_s=5.0,
        max_restarts=3, restart_backoff_s=0.1,
        queue_limit=max(64, 4 * mix["sessions"] * mix["turns"]),
    )

    def mk_replica(i: int):
        def factory():
            eng = ContinuousEngine(
                cfg, params, max_slots=args.max_batch,
                kv_block=mix["block"],
                prefill_chunk=args.prefill_chunk or None,
            )
            # Retention on BOTH legs: the engine keeps completed
            # conversations' prefix blocks either way — the legs
            # differ only in whether the router exploits them.
            eng.prefix_retain_max = 64
            eng.prefix_advertise_max = 64
            return eng

        sup = EngineSupervisor(
            factory, resilience=res,
            prefill_tokens_per_step=args.prefill_budget,
        )
        server = ReplicaServer(
            SupervisorBackend(sup, request_timeout_s=90.0),
            replica_id=f"chat-r{i}",
        ).start()
        return sup, server

    replicas = [mk_replica(i) for i in range(n)]
    ms = FleetMembership(fail_threshold=2)
    for _, server in replicas:
        ms.register(server.replica_id, server.endpoint)
    prefix_cfg = None
    if prefix_aware:
        prefix_cfg = PrefixConfig(kv_block=mix["block"], weight=1.0,
                                  pull_timeout_s=10.0)
    router = RouterServer(
        ms, config=RouterConfig(retries=2, request_timeout_s=90.0,
                                probe_interval_s=0.05),
        prefix=prefix_cfg,
    ).start()
    ms.probe(http_probe)
    router_as_backend = Replica(id="router", endpoint=router.endpoint)

    results = []
    results_lock = threading.Lock()

    def run_session(sid, user_turns, steps):
        history = None
        for turn in user_turns:
            prompt = (turn if history is None
                      else np.concatenate([history, turn]))
            t0 = time.perf_counter()
            try:
                status, payload = http_send(
                    router_as_backend,
                    {"tokens": prompt[None, :].tolist(),
                     "num_steps": steps, "session": sid,
                     "timing": True},
                    90.0,
                )
            except Exception as exc:  # noqa: BLE001 — transport loss
                with results_lock:
                    results.append({"tokens": None, "latency_s": 0.0,
                                    "ttft_s": 0.0, "itls": [],
                                    "error": repr(exc)})
                return
            latency = time.perf_counter() - t0
            if status != 200 or not payload.get("tokens"):
                with results_lock:
                    results.append({
                        "tokens": None, "latency_s": 0.0, "ttft_s": 0.0,
                        "itls": [], "error": f"{status}:"
                        f"{payload.get('code', 'untyped')}",
                    })
                return
            timing = (payload.get("timing") or [{}])[0]
            ttft_ms = timing.get("ttft_ms")
            out = payload["tokens"][0]
            with results_lock:
                results.append({
                    "tokens": out,
                    "latency_s": latency,
                    "ttft_s": (ttft_ms / 1e3 if ttft_ms is not None
                               else latency),
                    "itls": [g / 1e3
                             for g in timing.get("itl_ms", ())],
                    "error": None,
                })
            history = np.concatenate(
                [prompt, np.asarray(out, np.int32)]
            )
            if mix["think_ms"]:
                time.sleep(mix["think_ms"] / 1e3)

    def fleet_saved():
        s = i = 0
        for sup, _ in replicas:
            kv = sup.debug_snapshot().get("kv_cache") or {}
            s += kv.get("prefill_tokens_saved", 0)
            i += kv.get("ship_tokens_ingested", 0)
        return s, i

    # Untimed warmup: one throwaway conversation covering every turn
    # shape, so the prefill/join executables compile OFF the clock —
    # the timed pair then compares routing policy, not which leg ran
    # first against cold jit caches.
    warm = build_chat_sessions(dict(mix, sessions=1),
                               args.seed + 7919, args.vocab)
    run_session("warmup-0", warm[0][1], warm[0][2])
    results.clear()
    saved0, ingested0 = fleet_saved()

    threads = [
        threading.Thread(target=run_session, args=s, daemon=True)
        for s in sessions
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600.0)
    wall_s = time.perf_counter() - t0

    # Engine-observed ground truth, summed over the fleet: prompt
    # tokens whose K/V was NOT recomputed (local prefix joins) plus
    # tokens that arrived as shipped rows (cross-replica pulls) —
    # timed sessions only (the warmup baseline is subtracted).
    saved1, ingested1 = fleet_saved()
    saved, ingested = saved1 - saved0, ingested1 - ingested0
    rsnap = router.router.snapshot()
    stats = {
        "sessions": mix["sessions"],
        "turns": mix["turns"],
        "replicas": n,
        "prefix_aware": prefix_aware,
        "prefill_tokens_saved": saved,
        "ship_tokens_ingested": ingested,
        "max_batch": args.max_batch,
    }
    if prefix_aware:
        pfx = rsnap.get("prefix") or {}
        stats["router_prefix"] = {
            k: pfx.get(k, 0)
            for k in ("hits", "pulls", "pull_misses", "pull_fallbacks",
                      "tokens_saved", "affinity_routes")
        }
    router.stop()
    for sup, server in replicas:
        server.stop()
        sup.stop(timeout=30.0)
    line = leg_summary(name, wall_s, results, stats)
    return line


def run_fleet_prefix_legs(cfg, params, args, smoke: bool) -> list[dict]:
    """The ISSUE-16 acceptance pair: the IDENTICAL seeded multi-turn
    chat mix through (1) the prefix-aware router (scoring + session
    affinity + pulls) and (2) the plain least-loaded router, over
    engine-identical fleets. The prefix line carries the
    saved/TTFT-p50 ratios hardware rounds key on."""
    from dataclasses import replace

    mix = SMOKE_CHAT_MIX if smoke else CHAT_MIX
    # A conversation's final turn is turns*(user_tokens+steps) tokens;
    # the bench cfg's max_seq_len must hold it (power of two, ≥64).
    need = mix["turns"] * (mix["user_tokens"] + mix["steps"])
    seq = max(64, 1 << (need - 1).bit_length())
    chat_cfg = replace(cfg, max_seq_len=seq)

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import Transformer

    chat_params = Transformer(chat_cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    sessions = build_chat_sessions(mix, args.seed, args.vocab)
    prefix = _run_chat_leg("fleet_prefix_chat", chat_cfg, chat_params,
                           sessions, mix, args, prefix_aware=True)
    base = _run_chat_leg("fleet_lru_chat", chat_cfg, chat_params,
                         sessions, mix, args, prefix_aware=False)
    # The acceptance ratios: >1 saved ratio (prefix-aware reuses more
    # prefill) and <1 TTFT p50 ratio (cheaper prefill, faster first
    # token) at comparable tails.
    base_saved = base["prefill_tokens_saved"] + \
        base["ship_tokens_ingested"]
    pfx_saved = prefix["prefill_tokens_saved"] + \
        prefix["ship_tokens_ingested"]
    prefix["prefill_tokens_saved_vs_baseline"] = round(
        pfx_saved / max(1, base_saved), 3
    )
    if base["value"]:
        prefix["vs_baseline"] = round(
            prefix["value"] / base["value"], 3
        )
    if base["ttft_p50_ms"]:
        prefix["ttft_p50_vs_baseline"] = round(
            prefix["ttft_p50_ms"] / base["ttft_p50_ms"], 3
        )
    prefix["baseline_ttft_p50_ms"] = base["ttft_p50_ms"]
    prefix["baseline_ttft_p99_ms"] = base["ttft_p99_ms"]
    return [prefix, base]


def _run_tier_leg(name, cfg, params, sessions, mix, args, *,
                  tiered: bool):
    """One tier leg: a single supervised-free paged engine with a block
    pool sized to hold ONE conversation plus ``pool_extra`` headroom
    (prefix retention on — the PR 16 baseline), sessions replayed
    round-robin closed-loop so every retained prefix is reclaimed by
    the other sessions' traffic before its own next turn. ``tiered``
    attaches the host-RAM KV tier (reclaims SPILL, resumes RESTORE —
    serve/tier.py); the recompute leg drops evictions on the floor.
    Returns (line, outputs) — greedy decoding, so outputs must match
    across the pair."""
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.scheduler import (
        ContinuousScheduler,
        ServeRequest,
    )
    from tf_operator_tpu.serve.tier import HostTier

    need = mix["turns"] * (mix["user_tokens"] + mix["steps"])
    blocks = -(-need // mix["block"]) + mix["pool_extra"]
    engine = ContinuousEngine(
        cfg, params, max_slots=args.max_batch, kv_block=mix["block"],
        kv_blocks=blocks, prefill_chunk=args.prefill_chunk or None,
    )
    # Equal HBM budget on BOTH legs: same pool, same retention cap —
    # the legs differ only in what happens to a reclaimed prefix.
    engine.prefix_retain_max = mix["retain"]
    if tiered:
        engine.host_tier = HostTier(64 << 20)
    sched = ContinuousScheduler(
        engine, prefill_tokens_per_step=args.prefill_budget
    ).start()

    def one_turn(sid, prompt, steps):
        t1 = time.perf_counter()
        req = sched.submit_request(
            ServeRequest(prompt[None, :], int(steps), session=sid),
            timeout=300.0,
        )
        latency = time.perf_counter() - t1
        return {
            "tokens": [int(t) for t in req.out],
            "latency_s": latency,
            "ttft_s": req.ttft if req.ttft is not None else latency,
            "itls": req.itl_values(),
            "error": None,
        }

    def play(tag, convs, sink=None, resume_sink=None):
        history = {}
        for turn_idx in range(mix["turns"]):
            for sid, turns, steps in convs:
                if turn_idx >= len(turns):
                    continue
                prev = history.get(sid)
                prompt = (turns[turn_idx] if prev is None
                          else np.concatenate([prev, turns[turn_idx]]))
                rec = one_turn(f"{tag}{sid}", prompt, steps)
                if sink is not None:
                    sink.append(rec)
                if resume_sink is not None and turn_idx:
                    resume_sink.append(rec["ttft_s"])
                history[sid] = np.concatenate(
                    [prompt, np.asarray(rec["tokens"], np.int32)]
                )

    # Untimed warmup: TWO throwaway conversations covering every turn
    # shape compile prefill/decode off the clock — two, so the tight
    # pool evicts one's prefix under the other's traffic and the tier
    # leg exercises a full spill->restore round (the host->device
    # upload path compiles here, not on the timed clock).
    play("warm-", build_chat_sessions(dict(mix, sessions=2),
                                      args.seed + 7919, args.vocab))
    kv0 = engine.kv_debug()
    saved0 = kv0.get("prefill_tokens_saved", 0)
    restores0 = (kv0.get("tier") or {}).get("restores", 0)

    results, resume_ttfts = [], []
    t0 = time.perf_counter()
    play("", sessions, sink=results, resume_sink=resume_ttfts)
    wall_s = time.perf_counter() - t0

    kv = engine.kv_debug()
    stats = {
        "mix": "tier_resume",
        "sessions": mix["sessions"],
        "turns": mix["turns"],
        "tiered": tiered,
        "kv_pool_blocks": blocks,
        "kv_block": mix["block"],
        # Engine-observed ground truth: prompt tokens whose K/V was not
        # recomputed (prefix joins) over the TIMED sessions — on the
        # tier leg, restores feed this; on the recompute leg the tight
        # pool has already dropped the prefix by resume time.
        "prefill_tokens_saved": kv.get("prefill_tokens_saved", 0)
        - saved0,
        "resume_ttft_p50_ms": round(
            percentile(resume_ttfts, 0.5) * 1e3, 1
        ),
        "decode_step_compiles": engine.decode_step_compiles,
        "warmup_compiles": engine.warmup_compiles,
        # The resource caveat, as on the disagg line: restore's win is
        # host->device block upload vs recompute; a 1-core CPU round
        # prices both on the same core and the TTFT ratio can invert —
        # the saved ratio and the outputs-match pin are the mechanism
        # proof either way.
        "host_cpus": os.cpu_count(),
    }
    if tiered:
        tier = kv.get("tier") or {}
        stats["tier"] = {
            "bytes_used": tier.get("bytes_used", 0),
            "spills": tier.get("spills", 0),
            "hits": tier.get("hits", 0),
            "evictions": tier.get("evictions", 0),
            "restores": tier.get("restores", 0) - restores0,
            "restore_tokens": tier.get("restore_tokens", 0),
        }
    sched.stop(timeout=30.0)
    line = leg_summary(name, wall_s, results, stats)
    return line, [r["tokens"] for r in results]


def run_tier_legs(cfg, params, args, smoke: bool) -> list[dict]:
    """The ISSUE-17 acceptance pair: the IDENTICAL seeded session-resume
    mix at the IDENTICAL HBM block budget, once with the host-RAM KV
    tier attached and once recomputing every evicted prefix. The tier
    line carries the saved/TTFT ratios and the bench-scale bit-identity
    pin (``outputs_match_baseline``)."""
    from dataclasses import replace

    mix = SMOKE_TIER_MIX if smoke else TIER_MIX
    # A conversation's final turn is turns*(user_tokens+steps) tokens;
    # the bench cfg's max_seq_len must hold it (power of two, >= 64).
    need = mix["turns"] * (mix["user_tokens"] + mix["steps"])
    seq = max(64, 1 << (need - 1).bit_length())
    tier_cfg = replace(cfg, max_seq_len=seq)

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import Transformer

    tier_params = Transformer(tier_cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    sessions = build_chat_sessions(mix, args.seed, args.vocab)
    tier, tier_out = _run_tier_leg("tier_resume", tier_cfg, tier_params,
                                   sessions, mix, args, tiered=True)
    base, base_out = _run_tier_leg("tier_recompute", tier_cfg,
                                   tier_params, sessions, mix, args,
                                   tiered=False)
    # The acceptance ratios: > 1 saved ratio (restores turn evictions
    # back into prefix joins) and, on hardware, < 1 resume-TTFT ratio
    # (uploading spilled blocks beats recomputing them). Greedy
    # decoding makes the output comparison exact — the spill->restore
    # bit-identity pin at bench scale.
    tier["outputs_match_baseline"] = tier_out == base_out
    tier["prefill_tokens_saved_vs_baseline"] = round(
        tier["prefill_tokens_saved"]
        / max(1, base["prefill_tokens_saved"]), 3
    )
    if base["value"]:
        tier["vs_baseline"] = round(tier["value"] / base["value"], 3)
    if base["resume_ttft_p50_ms"]:
        tier["resume_ttft_p50_vs_baseline"] = round(
            tier["resume_ttft_p50_ms"] / base["resume_ttft_p50_ms"], 3
        )
    tier["baseline_resume_ttft_p50_ms"] = base["resume_ttft_p50_ms"]
    tier["baseline_ttft_p50_ms"] = base["ttft_p50_ms"]
    return [tier, base]


def build_interference_schedule(cap: dict, seed: int, vocab: int):
    """Deterministic interference traffic: short decode-heavy requests
    with a long prefill landing every ``long_every`` arrivals."""
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    shapes = list(cap["shapes"])
    for i in range(cap["requests"]):
        if i and i % cap["long_every"] == 0:
            p, steps = cap["long_prompt"], cap["long_steps"]
        else:
            p, steps = shapes[int(rng.integers(0, len(shapes)))]
        prompt = rng.integers(0, vocab, (1, p)).astype(np.int32)
        out.append((t, prompt, steps))
        t += float(rng.exponential(cap["gap_ms"])) / 1e3
    return out


def _run_interference_leg(name, cfg, params, schedule, cap, *,
                          disagg: bool) -> dict:
    """One interference leg over real HTTP: a supervised continuous
    engine behind a replica server, fronted by the plain router
    (time-shared leg) or the two-stage disagg router over a 2-replica
    prefill pool with one prefill replica killed mid-run (disagg leg).
    Same transport both ways, so the comparison is the PREFILL
    PLACEMENT, not HTTP overhead. TTFT/ITL come from the replica's own
    per-request timing breakdown — engine-observed first-token time and
    decode-step gaps, identical semantics on both legs."""
    from tf_operator_tpu.fleet.membership import FleetMembership, Replica
    from tf_operator_tpu.fleet.replica import (
        ReplicaServer,
        SupervisorBackend,
    )
    from tf_operator_tpu.fleet.router import (
        DisaggConfig,
        DisaggRouterServer,
        RouterConfig,
        RouterServer,
        http_probe,
        http_send,
    )
    from tf_operator_tpu.serve.disagg import PrefillServer, PrefillWorker
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.resilience import (
        EngineSupervisor,
        ResilienceConfig,
    )

    res = ResilienceConfig(
        queue_ttl_s=60.0, decode_deadline_s=90.0, watchdog_stall_s=10.0,
        max_restarts=3, restart_backoff_s=0.1,
        queue_limit=max(64, 4 * len(schedule)),
    )
    sup = EngineSupervisor(
        lambda: ContinuousEngine(
            cfg, params, max_slots=8, prefill_chunk=cap["chunk"],
            kv_block=cap["block"],
        ),
        resilience=res, prefill_tokens_per_step=cap["budget"],
    )
    decode_server = ReplicaServer(
        SupervisorBackend(sup, request_timeout_s=120.0),
        replica_id=f"{name}-d0",
    ).start()
    dms = FleetMembership(fail_threshold=2, name=name)
    dms.register(f"{name}-d0", decode_server.endpoint)
    rcfg = RouterConfig(retries=2, request_timeout_s=120.0,
                        probe_interval_s=0.1)
    prefill_servers = []
    if disagg:
        for i in range(2):
            prefill_servers.append(PrefillServer(
                PrefillWorker(cfg, params, prefill_chunk=cap["chunk"],
                              kv_block=cap["block"]),
                replica_id=f"{name}-p{i}",
            ).start())
        pms = FleetMembership(fail_threshold=2, name=f"{name}#prefill")
        for s in prefill_servers:
            pms.register(s.replica_id, s.endpoint, role="prefill")
        router = DisaggRouterServer(
            pms, dms, config=rcfg,
            disagg=DisaggConfig(ship_min_tokens=cap["ship_min"]),
        ).start()
        pms.probe(http_probe)
    else:
        router = RouterServer(dms, config=rcfg).start()
    dms.probe(http_probe)

    outcomes: list = []
    olock = threading.Lock()
    front = Replica(id="router", endpoint=router.endpoint)

    def submit(prompt, steps):
        status, payload = http_send(
            front,
            {"tokens": prompt.tolist(), "num_steps": steps,
             "timing": True},
            120.0,
        )
        with olock:
            outcomes.append((status, payload))
        if status == 200 and payload.get("tokens"):
            timing = (payload.get("timing") or [{}])[0]
            ttft = timing.get("ttft_ms")
            gaps = [g / 1e3 for g in timing.get("itl_ms", ())]
            return (payload["tokens"][0],
                    ttft / 1e3 if ttft is not None else None, gaps)
        raise RuntimeError(f"{status}:{payload.get('code', 'untyped')}")

    run_schedule(schedule, submit)  # untimed warmup, pool whole
    outcomes.clear()
    killer = None
    if disagg:
        # Kill one prefill replica as the mid-run arrivals land: the
        # stage-1 retry re-prefills elsewhere; lost must stay 0.
        kill_at = schedule[len(schedule) // 2][0]
        killer = threading.Timer(max(0.05, kill_at),
                                 prefill_servers[0].kill)
        killer.start()
    wall_s, results = run_schedule(schedule, submit)
    if killer is not None:
        killer.cancel()

    ok = sum(1 for s, p in outcomes
             if s == 200 and not p.get("deadline_exceeded"))
    partial = sum(1 for s, p in outcomes
                  if s == 200 and p.get("deadline_exceeded"))
    typed = sum(1 for s, p in outcomes
                if s is not None and s >= 400 and p.get("code"))
    untyped = sum(1 for s, p in outcomes
                  if s is None or (s >= 400 and not p.get("code")))
    lost = len(schedule) - len(outcomes)
    shipped_joins = sum(
        1 for s, p in outcomes
        if s == 200 and (p.get("timing") or [{}])[0].get("shipped_kv")
    )
    kv = sup.engine.kv_debug() if sup.scheduler is not None else {}
    stats = {
        "mix": "interference",
        "resolved": len(outcomes),
        "lost": lost,
        "ok": ok,
        "deadline_partials": partial,
        "typed_errors": typed,
        "untyped_errors": untyped,
        "long_prompt": cap["long_prompt"],
        "long_every": cap["long_every"],
        "prefill_chunk": cap["chunk"],
        "prefill_budget": cap["budget"],
        "deadline_budget_ms": round(res.decode_deadline_s * 1e3, 1),
        "shipped_joins": shipped_joins,
        "shipments_ingested": kv.get("shipments_ingested", 0),
        "decode_step_compiles": (
            sup.engine.decode_step_compiles
            if sup.scheduler is not None else None
        ),
        "warmup_compiles": (
            sup.engine.warmup_compiles
            if sup.scheduler is not None else None
        ),
    }
    # The resource model matters for reading the tails: disaggregation
    # buys its win with DEDICATED prefill hardware. On a host whose
    # prefill "replicas" share the decode device's cores (host_cpus <=
    # the replica count — CI runs on 1), the pair measures the
    # MECHANISM (zero lost, longs shipped, typed fallbacks) and the
    # tail ratios invert, exactly like the tp pair's CPU line; the
    # hardware rounds report the real ratios through this same
    # plumbing.
    stats["host_cpus"] = os.cpu_count()
    if disagg:
        stats["prefill_replicas"] = 2
        stats["killed_prefill_replicas"] = 1
        stats["ship"] = router.router.snapshot()["ship"]
    router.stop()
    for s in prefill_servers[1:]:
        s.stop()
    decode_server.stop()
    sup.stop(timeout=30.0)
    line = leg_summary(name, wall_s, results, stats)
    line["errors"] = untyped + lost  # typed resolutions are contract
    return line


def run_disagg_legs(args, smoke: bool) -> list[dict]:
    """The ROADMAP item-2 interference pair: disaggregated vs
    time-shared on the identical seeded schedule. The disagg line's
    ``vs_baseline`` is disagg/timeshared tokens/sec; its
    ``ttft_p99_vs_baseline`` / ``itl_p99_vs_baseline`` are the ratios
    the acceptance pin reads (< 1.0 = disaggregation wins that tail)."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )

    cap = SMOKE_INTERFERENCE if smoke else INTERFERENCE
    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=4,
        n_layers=args.layers, d_ff=args.d_model * 2,
        max_seq_len=cap["seq"], dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    schedule = build_interference_schedule(cap, args.seed, args.vocab)
    base = _run_interference_leg(
        "timeshared_interference", cfg, params, schedule, cap,
        disagg=False,
    )
    dis = _run_interference_leg(
        "disagg_interference", cfg, params, schedule, cap,
        disagg=True,
    )
    if base["value"]:
        dis["vs_baseline"] = round(dis["value"] / base["value"], 3)
    dis["baseline_ttft_p99_ms"] = base["ttft_p99_ms"]
    dis["baseline_itl_p99_ms"] = base["itl_p99_ms"]
    if base["ttft_p99_ms"]:
        dis["ttft_p99_vs_baseline"] = round(
            dis["ttft_p99_ms"] / base["ttft_p99_ms"], 3
        )
    if base["itl_p99_ms"]:
        dis["itl_p99_vs_baseline"] = round(
            dis["itl_p99_ms"] / base["itl_p99_ms"], 3
        )
    return [dis, base]


def run_coalesce(cfg, params, schedule, args, *, decode_fn=None,
                 name="coalesce") -> dict:
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import generate
    from tf_operator_tpu.serve.coalesce import Coalescer

    lock = threading.Lock()

    if decode_fn is None:
        def plain_decode(rows, num_steps):
            return generate(cfg, params, rows, num_steps=num_steps)

        decode_fn = plain_decode
    inner_decode = decode_fn

    def decode_fn(rows, num_steps):  # noqa: F811 — locked wrapper
        with lock:
            return inner_decode(rows, num_steps)

    def one_pass(timed: bool):
        stop = threading.Event()
        co = Coalescer(args.window_ms / 1e3, args.max_batch, decode_fn,
                       stop)
        t = threading.Thread(target=co.loop, daemon=True)
        t.start()

        def submit(prompt, steps):
            t0 = time.perf_counter()
            out = co.submit(jnp.asarray(prompt), steps)
            # Lock-step: the client sees nothing before the whole batch
            # finishes — TTFT is response latency (None → measured by
            # the caller), and the only honest ITL is the effective
            # per-token delivery rate (latency / tokens, one pooled
            # sample per request).
            dt = time.perf_counter() - t0
            return (np.asarray(out)[0].tolist(), None,
                    [dt / max(1, steps)])

        wall_s, results = run_schedule(schedule, submit)
        stats = {
            "coalesced_batches": co.batches,
            "max_batch_rows": co.max_rows_seen,
            "window_ms": args.window_ms,
            "max_batch": args.max_batch,
        }
        stop.set()
        t.join(timeout=30.0)
        return wall_s, results, stats

    one_pass(timed=False)
    wall_s, results, stats = one_pass(timed=True)
    return leg_summary(name, wall_s, results, stats)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--engine",
                   choices=("continuous", "coalesce", "both", "chaos",
                            "fleet", "fleet-prefix", "disagg", "spec",
                            "tier", "constrain"),
                   default="both",
                   help="'chaos' runs ONLY the seeded fault-injection "
                        "mix (supervised engine, step crash + stall "
                        "mid-run); 'fleet' the router-fronted replica "
                        "fleet with one replica killed mid-run; "
                        "'fleet-prefix' the ISSUE-16 multi-turn chat "
                        "pair: prefix-aware routing (scoring + session "
                        "affinity + cross-replica pulls) vs the plain "
                        "least-loaded router on the identical seeded "
                        "session mix; "
                        "'disagg' the ROADMAP item-2 interference pair "
                        "(long prefills + latency-sensitive decodes, "
                        "disaggregated prefill pool vs the time-shared "
                        "engine, one prefill replica killed mid-run); "
                        "'spec' the ISSUE-15 triple: batch-wide "
                        "speculative continuous engine vs the plain "
                        "continuous engine vs legacy --spec-k coalesce "
                        "on one seeded schedule with a quick-trained "
                        "target/draft pair (accept_rate on the line); "
                        "'tier' the ISSUE-17 session-resume pair: the "
                        "host-RAM KV tier (spill on eviction, restore "
                        "on resume) vs recompute at the identical "
                        "tight HBM block budget; "
                        "'constrain' the ISSUE-19 structured-decoding "
                        "pair: the identical seeded schedule free vs "
                        "with every other request under a compiled "
                        "JSON-schema grammar program (grammar_valid "
                        "and zero-recompile pins, vs_baseline = the "
                        "mask overhead)")
    p.add_argument("--spec-k", type=int, default=8,
                   help="draft proposals per round for --engine spec "
                        "(CPU rounds need a large k: per-round "
                        "overheads amortize over the accepted window, "
                        "and the chain-task draft accepts ~0.97)")
    p.add_argument("--fleet-replicas", type=int, default=4,
                   help="replica count for --engine fleet")
    p.add_argument("--tp", type=int, default=0,
                   help="run ONLY the SPMD tensor-parallel pair: the "
                        "continuous engine on an N-device tp mesh vs "
                        "the single-device engine on the identical "
                        "schedule (vs_baseline = tpN/tp1). On CPU the "
                        "devices are forced via the XLA host-device "
                        "trick before jax imports")
    p.add_argument("--dp", type=int, default=1,
                   help="with --tp: run ONLY the pod-scale pair — the "
                        "continuous engine on the 2-D tp x dp mesh "
                        "(tp*dp devices; slot state + paged pool "
                        "blocks dp-sharded) vs the same tp at dp=1 on "
                        "the identical schedule (vs_baseline = "
                        "tpNdpM/tpNdp1); must divide --max-batch")
    p.add_argument("--requests", type=int, default=None)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mean-gap-ms", type=float, default=None,
                   help="mean open-loop interarrival gap (seeded "
                        "exponential)")
    p.add_argument("--window-ms", type=float, default=25.0,
                   help="coalesce leg's batch window")
    p.add_argument("--prefill-chunk", type=int, default=16)
    p.add_argument("--prefill-budget", type=int, default=64)
    p.add_argument("--d-model", type=int, default=None)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--skip-prefix-mix", action="store_true",
                   help="skip the long-context + shared-prefix capacity "
                        "section (paged vs dense at one byte budget)")
    args = p.parse_args(argv)

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    shapes = SMOKE_SHAPES if smoke else SHAPES
    if args.requests is None:
        args.requests = 12 if smoke else 48
    if args.mean_gap_ms is None:
        args.mean_gap_ms = 2.0 if smoke else 5.0
    if args.d_model is None:
        args.d_model = 32 if smoke else 64
    if smoke:
        args.prefill_chunk = min(args.prefill_chunk, 4)
    if args.tp > 1:
        # BEFORE the jax import below: on the CPU platform the mesh
        # devices come from the host-device trick (a no-op flag on real
        # hardware, where jax.devices() are the chips). ONE
        # implementation of the raise-a-smaller-pinned-count rule —
        # serve_tp_check owns it (bench.py's smoke mode pins 1 for its
        # in-process sections and would otherwise starve the mesh).
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from serve_tp_check import _force_host_devices

        _force_host_devices(args.tp * max(1, args.dp))

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )

    max_seq = max(p_ + s for p_, s in shapes)
    if args.prefill_chunk:
        max_seq = max(
            max_seq,
            max(-(-p_ // args.prefill_chunk) * args.prefill_chunk + s
                for p_, s in shapes),
        )
    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=4,
        n_layers=args.layers, d_ff=args.d_model * 2,
        # Static cache rows per slot: the largest shape plus headroom,
        # rounded up — the cache read scales with this, as in serving.
        max_seq_len=max(64, 1 << (max_seq - 1).bit_length()),
        dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    schedule = build_schedule(
        args.requests, args.mean_gap_ms, args.seed, shapes, args.vocab
    )

    lines = []
    if args.tp > 1 and args.dp > 1 and args.engine != "spec":
        lines = run_tpdp_legs(cfg, params, schedule, args)
        for line in lines:
            print(json.dumps(line), flush=True)
        return 0 if all(not line["errors"] for line in lines) else 1
    if args.tp > 1 and args.engine != "spec":
        lines = run_tp_legs(cfg, params, schedule, args)
        for line in lines:
            print(json.dumps(line), flush=True)
        return 0 if all(not line["errors"] for line in lines) else 1
    if args.engine == "chaos":
        lines.append(run_chaos_leg(cfg, params, schedule, args))
    if args.engine == "fleet":
        lines.append(run_fleet_leg(cfg, params, schedule, args))
    if args.engine == "fleet-prefix":
        lines.extend(run_fleet_prefix_legs(cfg, params, args, smoke))
    if args.engine == "disagg":
        lines.extend(run_disagg_legs(args, smoke))
    if args.engine == "tier":
        lines.extend(run_tier_legs(cfg, params, args, smoke))
    if args.engine == "constrain":
        lines.extend(run_constrain_legs(cfg, params, args, smoke))
    if args.engine == "spec":
        mesh = None
        if args.tp > 1:
            # --engine spec --tp N: the WHOLE triple on an N-device tp
            # mesh (host devices on CPU — forced above) — the
            # acceptance pin runs at tp=1 AND tp=2.
            from tf_operator_tpu.parallel.mesh import create_mesh

            if len(jax.devices()) < args.tp:
                raise SystemExit(
                    f"serve_bench: --tp {args.tp} needs {args.tp} "
                    f"devices, have {len(jax.devices())}"
                )
            mesh = create_mesh({"tp": args.tp},
                               jax.devices()[: args.tp])
        lines.extend(run_spec_legs(cfg, schedule, args, smoke,
                                   mesh=mesh))
    if args.engine in ("continuous", "both"):
        lines.append(run_continuous(cfg, params, schedule, args))
    if args.engine in ("coalesce", "both"):
        lines.append(run_coalesce(cfg, params, schedule, args))
    if len(lines) == 2 and lines[1]["value"]:
        # The acceptance ratio: continuous over the legacy coalescer.
        lines[0]["vs_baseline"] = round(
            lines[0]["value"] / lines[1]["value"], 3
        )
    if args.engine == "both" and not args.skip_prefix_mix:
        lines.extend(run_capacity_mix(args, smoke))
    for line in lines:
        print(json.dumps(line), flush=True)
    return 0 if all(not line["errors"] for line in lines) else 1


if __name__ == "__main__":
    sys.exit(main())
