#!/usr/bin/env python
"""Fast KV-memory-hierarchy smoke: runs the `tier`-marked tests in
isolation (spill→restore bit-identity dense AND kv8, tier-off
equivalence, can-restore admission, warm advertisement/export/typed
tier_miss, HostTier byte-budget unit pins, the warm-holder fleet chaos
case), then one INLINE end-to-end spill→restore through a live paged
engine: serve a prompt, reclaim its retained prefix under simulated
pool pressure (the entry spills to the host tier), serve the identical
prompt again and assert the restored decode is bit-identical to solo
generate with the whole prefill skipped and zero decode recompiles.
The quick loop for iterating on tf_operator_tpu/serve/tier.py without
paying for the whole tier-1 run; the same tests also ride
tools/serve_smoke.py's default pass.

    python tools/tier_smoke.py             # tier tests + inline e2e
    python tools/tier_smoke.py -k kv8      # extra pytest args pass through
    python tools/tier_smoke.py --bench     # + the slow bench pair

Exit code is pytest's (or 1 if the e2e fails).
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def spill_restore_e2e() -> int:
    """One spill→restore round end-to-end: live engine, live serving
    loop, the restored decode pinned against solo generate and the
    tier's own counters."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
    )
    from tf_operator_tpu.serve.engine import ContinuousEngine
    from tf_operator_tpu.serve.scheduler import (
        ContinuousScheduler,
        ServeRequest,
    )
    from tf_operator_tpu.serve.tier import HostTier

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = ContinuousEngine(
        cfg, params, max_slots=2, kv_paged=True, kv_block=8
    )
    engine.prefix_retain_max = 16
    engine.host_tier = HostTier(16 << 20)
    sched = ContinuousScheduler(engine).start()
    try:
        prompt = np.random.default_rng(17).integers(
            0, cfg.vocab_size, (1, 13)
        ).astype(np.int32)
        steps = 16
        want = np.asarray(
            generate(cfg, params, jnp.asarray(prompt), steps)
        )[0].tolist()
        r1 = sched.submit_request(ServeRequest(prompt, steps),
                                  timeout=60.0)
        assert r1.out == want, "paged output != solo"
        # Pool pressure reclaims the retained prefix — it SPILLS.
        sched.call_engine(lambda e: e._evict_retained(until_free=10 ** 9))
        assert engine.blocks.used == 0, "spill left device blocks live"
        assert len(engine.host_tier) >= 1, "eviction did not spill"
        saved0 = engine.prefill_tokens_saved
        r2 = sched.submit_request(ServeRequest(prompt, steps,
                                               session="smoke"),
                                  timeout=60.0)
        assert r2.out == want, "restored output != solo"
        assert engine.tier_restores >= 1, "admission did not restore"
        assert engine.prefill_tokens_saved - saved0 >= prompt.shape[1], (
            "restore did not skip the prefill"
        )
        assert engine.decode_step_compiles == engine.warmup_compiles
        snap = engine.host_tier.snapshot()
        print(
            f"tier_smoke: spill→restore e2e ok (spills="
            f"{snap['spills']}, restores={engine.tier_restores}, "
            f"restored {engine.tier_restore_tokens} tokens, "
            f"{snap['bytes_used']} host bytes, zero decode recompiles)",
            flush=True,
        )
        return 0
    finally:
        sched.stop(timeout=30.0)


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    bench = "--bench" in args
    if bench:
        args.remove("--bench")
    marker = "tier" if bench else "tier and not slow"
    cmd = [
        sys.executable, "-m", "pytest",
        "tests/test_serve_tier.py", "tests/test_fleet_chaos.py",
        "-m", marker,
        "-q", "-p", "no:cacheprovider",
        *args,
    ]
    rc = subprocess.call(cmd, cwd=REPO_ROOT, env=env)
    if rc != 0:
        return rc
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    return spill_restore_e2e()


if __name__ == "__main__":
    raise SystemExit(main())
