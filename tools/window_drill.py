"""Smoke-drill every window_autorun stage on CPU (BENCH_SMOKE shapes).

The window daemon's stages must not fail on argument/plumbing bugs when
the real tunnel window opens — this drill runs the exact argv+env each
stage would use, with BENCH_SMOKE=1 forcing tiny shapes on the CPU
backend, and reports useful-line counts per stage. Run after any change
to bench.py / perf_probe.py / window_autorun.py:

    python tools/window_drill.py
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import window_autorun as wa  # noqa: E402


def main() -> int:
    failures = []
    for label, env_over, _budget in wa.STAGES:
        argv, env = wa.stage_argv(label, dict(env_over) if env_over else None)
        env["BENCH_SMOKE"] = "1"
        out_path = f"/tmp/drill_{label}.jsonl"
        t0 = time.monotonic()
        try:
            with open(out_path, "w") as out_f:
                proc = subprocess.run(
                    argv, env=env, stdout=out_f,
                    stderr=subprocess.PIPE, timeout=600,
                )
            rc: object = proc.returncode
            err_tail = proc.stderr.decode(errors="replace")[-500:]
        except subprocess.TimeoutExpired:
            rc, err_tail = "timeout", ""
        useful = wa._useful_lines(out_path, label)
        dt = time.monotonic() - t0
        status = "OK" if useful else "NO-DATA"
        if not useful:
            failures.append(label)
        print(f"{status:7s} {label:14s} rc={rc} {dt:5.1f}s "
              f"useful={useful}", flush=True)
        if not useful and err_tail:
            print(f"        stderr: {err_tail}", flush=True)
    print(f"drill: {len(wa.STAGES) - len(failures)}/{len(wa.STAGES)} stages "
          f"produced data" + (f"; FAILED: {failures}" if failures else ""))
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
