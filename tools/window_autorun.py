"""TPU-window auto-runner: poll the tunnel, pounce on an UP window.

The chip behind the axon tunnel is reachable only in short, unpredictable
windows (rounds 2-4 each saw 6-12 h outages around a ~35-min window).
This daemon replaces the passive watcher: it polls `jax.devices()` under
a timeout, and the moment the backend answers it runs the staged
measurement plan (STAGES below) — highest-value first, each stage its
own subprocess with a budget, tunnel re-checked between stages — so a
window is fully exploited even if it opens while nobody is watching.
The r05 first window (2026-08-01, 33 min) captured the core 7 stages
this way; the remaining stages resume automatically at the next UP.

Done-state is DERIVED FROM DISK (_done_from_disk): a stage whose
artifact under docs/$WINDOW_DIR_NAME/<stamp>/<stage>.jsonl holds useful
lines is never re-run, so daemon restarts (code updates, supervisor
relaunch after a crash) are free. Stage groups, in priority order:

  attribution  roofline/roofline2 (ceilings: chained matmul AND chained
               copy — one-shot probes under-read this time-sliced
               tunnel ~5x), qblock (dispatch-vs-direct arbitration —
               promoted to the front of the unmeasured set: the
               MAX_Q_BLOCK retune still awaits its data), kvblock
               (pallas paged-attend vs gather across kv_block sizes),
               synthetic (device-resident ResNet), convsweep,
               flashramp/flashblocks (8k ramp, Q-block A/Bs)
  artifact     bench_full (the complete bench.py run), serve
               (continuous-batching vs coalescer mixed traffic),
               bench_resnet2 + resnet_resident (re-measures: mfu gate,
               HBM-resident input mode)
  secondary    flashsweep, h2d, lm A/B (flash vs xla), lmsweep,
               decodesweep, decodelong, specdecode, input, fwd_split,
               stem

Everything lands under docs/$WINDOW_DIR_NAME/<UTC stamp>/<stage>.jsonl
(default window_r05); stderr per stage under the same dir. See the
"Window-capture runbook" in docs/developer_guide.md. Usage:
    nohup python tools/window_autorun.py >> /tmp/autorun.log 2>&1 &
"""

from __future__ import annotations

import datetime
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_ROOT = os.path.join(
    REPO, "docs", os.environ.get("WINDOW_DIR_NAME", "window_r05")
)
POLL_S = 150.0
PROBE_TIMEOUT_S = 45.0

# (label, env overrides ({"PROBE": name} = perf_probe stage, {"BENCH":
# section} = bench --section stage, None = full bench), budget seconds).
STAGES = [
    ("roofline", {"PROBE": "roofline"}, 300.0),
    # FIRST unmeasured stage of the next window: the in-process
    # dispatch-vs-direct Q-block A/B (r05: direct bq1024 measured 14.0
    # TFLOP/s but the dispatch path read 11.5 minutes later — interleaved
    # legs decide config effect vs chip drift). The MAX_Q_BLOCK 512→1024
    # retune shipped ahead of this arbitration data (ADVICE r5), and at
    # its old slot — behind the 3600s bench_full — a short window never
    # reached it; the revert trigger it arms is documented at
    # ops/flash_attention.py MAX_Q_BLOCK.
    ("qblock", {"PROBE": "qblock"}, 600.0),
    # Paged-attention kernel A/B (ISSUE 18): pallas vs gather decode
    # attend across kv_block sizes with lanes spread over occupancy —
    # the hardware ratios for the per-lane HBM-bounding claim (the CPU
    # interpret line is mechanism proof only). Rides directly behind
    # qblock so one short window arbitrates BOTH block-geometry
    # questions.
    ("kvblock", {"PROBE": "kvblock"}, 600.0),
    ("synthetic", {"PROBE": "synthetic"}, 900.0),
    ("convsweep", {"PROBE": "convsweep"}, 600.0),
    ("flashramp", {"PROBE": "flashramp"}, 600.0),
    ("flashblocks", {"PROBE": "flashblocks"}, 600.0),
    ("bench_full", None, 3600.0),
    ("flashsweep", {"PROBE": "flashsweep"}, 900.0),
    ("h2d", {"PROBE": "h2d"}, 180.0),
    # Re-run the roofline with the scan-chained copy added after the r05
    # first window (single-execution copy read 77 GB/s while a fused
    # decode scan sustained 365 — the chained leg measures the real HBM
    # ceiling); also re-anchors ceilings for the same-window lm/decode
    # stages below.
    ("roofline2", {"PROBE": "roofline"}, 300.0),
    # Continuous-batching serving line (tools/serve_bench.py via bench
    # --section serve): mixed-length open-loop traffic, continuous engine
    # vs the legacy coalescer — the sustained-serving companion to the
    # static-batch decode lines.
    ("serve", {"BENCH": "serve"}, 700.0),
    # NEW headline candidate: dataset resident in HBM, augmentation on
    # device (train/device_input.py) — the designed answer to this
    # environment's ~27 MB/s h2d. Expected to land near the synthetic
    # 2,533 img/s WITH augmentation on the clock.
    ("resnet_resident", {"BENCH": "resnet_resident"}, 900.0),
    ("lm_ab_flash", {"BENCH": "lm", "TPU_OPERATOR_ATTN": ""}, 1100.0),
    ("lm_ab_xla", {"BENCH": "lm", "TPU_OPERATOR_ATTN": "xla"}, 1100.0),
    ("lmsweep", {"PROBE": "lmsweep"}, 1500.0),
    # 4 weight/cache variants (bf16, int8, kv8, int8kv8) x 2 batch sizes.
    ("decodesweep", {"PROBE": "decodesweep"}, 1400.0),
    # Long-context cache ladder: bf16 -> int8 cache (2x) -> GQA (4x) ->
    # both (8x) at the shape where the cache dominates the per-step read.
    ("decodelong", {"PROBE": "decodelong"}, 1500.0),
    # Speculative-decoding component costs (plain vs self-draft vs cold
    # draft): the acceptance-curve endpoints for models/spec_decode.py.
    ("specdecode", {"PROBE": "specdecode"}, 900.0),
    # Batch-wide speculative SERVING triple (ISSUE 15): spec continuous
    # engine vs plain continuous vs legacy --spec-k coalesce on one
    # seeded schedule — the hardware ratios for the acceptance pin (the
    # CPU line is a floor: compute-bound hosts can't show the
    # weight-read amortization the verify chunk buys).
    ("serve_spec", {"BENCH": "serve_spec"}, 700.0),
    # Tail attribution: host input pipeline (CPU-only, cheap) and the
    # ResNet fwd/bwd split — consulted if the synthetic-vs-bench split
    # points at input/transfer or the gradient path respectively.
    ("input", {"PROBE": "input"}, 300.0),
    ("fwd_split", {"PROBE": "fwd_split"}, 600.0),
    # Re-measure ONLY the resnet section: the first-window bench_full
    # artifact predates the mfu sanity gate (it carries the implausible
    # xla-cost-analysis mfu=0.001), and re-running all of bench_full
    # (3600s) would crowd out the unmeasured stages above.
    ("bench_resnet2", {"BENCH": "resnet"}, 900.0),
    # Last: the most expensive stage for its marginal value (two full
    # synthetic compiles on a 1-CPU host — the r05 window proved 900s is
    # not enough for both; the primary b256 number comes from the
    # synthetic stage anyway, this only adds the s2d-stem A/B).
    ("stem", {"PROBE": "stem"}, 1800.0),
]


def log(msg: str) -> None:
    print(f"{datetime.datetime.utcnow():%H:%M:%S} {msg}", flush=True)


def tunnel_up() -> bool:
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, timeout=PROBE_TIMEOUT_S, text=True,
            # The probe's jax import is CPU-heavy for seconds; at nice 19
            # it cannot contend with a concurrent (driver) bench's
            # CPU-side latency fleet (the BENCH_r04 submit inflation).
            preexec_fn=lambda: os.nice(19),
        )
        return out.stdout.strip().endswith("1")
    except subprocess.TimeoutExpired:
        return False
    except Exception:
        return False


def stage_argv(label: str, env_over: dict | None) -> tuple[list, dict]:
    env = dict(os.environ)
    env["BENCH_WATCHDOG_S"] = "0"  # our own budget is the watchdog
    if env_over and "PROBE" in env_over:
        env.update(env_over)
        return [sys.executable, os.path.join(REPO, "perf_probe.py")], env
    if env_over and "BENCH" in env_over:
        section = env_over.pop("BENCH")
        env.update(env_over)
        return (
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--section", section],
            env,
        )
    # full bench: keep its own watchdog + per-section isolation
    env.pop("BENCH_WATCHDOG_S")
    return [sys.executable, os.path.join(REPO, "bench.py")], env


def _useful_lines(path: str, label: str) -> int:
    """Count result lines that represent real (hardware) data: JSON lines
    with no "error" key — and for the full bench, not the CPU-only
    submit-latency line, which lands even when the tunnel is down (that is
    exactly the BENCH_r03 rc=3 shape that must NOT mark the stage done)."""
    import json as _json

    n = 0
    try:
        with open(path) as f:
            for line in f:
                if not line.startswith("{"):
                    continue
                try:
                    obj = _json.loads(line)
                except ValueError:
                    continue
                if "error" in obj:
                    continue
                if obj.get("metric", "").startswith("tpujob_submit"):
                    continue
                n += 1
    except OSError:
        pass
    return n


def _foreign_bench_running() -> bool:
    """True when a bench.py/perf_probe.py process NOT descended from this
    daemon is running — e.g. the driver's round-end bench. The daemon
    must yield the chip to it rather than contend (a shared single chip
    through the tunnel serializes executions; contention distorts both
    runs' numbers)."""
    me = os.getpid()
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        pid_i = int(pid)
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                argv = f.read().split(b"\0")
            # Structural match only — argv[1] IS the script path. A text
            # grep would permanently match the driver's own wrapper shell
            # (its huge -c string mentions bench.py).
            if len(argv) < 2 or not argv[1].endswith(
                (b"/bench.py", b"bench.py", b"/perf_probe.py",
                 b"perf_probe.py")
            ):
                continue
            if b"python" not in os.path.basename(argv[0]):
                continue
            # Walk ancestry: skip processes this daemon spawned.
            cur = pid_i
            mine = False
            for _ in range(10):
                if cur == me:
                    mine = True
                    break
                with open(f"/proc/{cur}/stat") as f:
                    ppid = int(f.read().rsplit(")", 1)[1].split()[1])
                if ppid in (0, 1):
                    break
                cur = ppid
            if not mine:
                return True
        except (OSError, ValueError, IndexError):
            continue
    return False


def run_window(done: set) -> None:
    if all(label in done for label, _, _ in STAGES):
        return
    stamp = datetime.datetime.utcnow().strftime("%Y%m%dT%H%M%S")
    out_dir = os.path.join(OUT_ROOT, stamp)
    os.makedirs(out_dir, exist_ok=True)
    log(f"UP — window sequence starting, artifacts in {out_dir}")
    for label, env_over, budget in STAGES:
        if label in done:
            continue
        waited = 0.0
        while _foreign_bench_running() and waited < 3600:
            if waited == 0:
                log("foreign bench running (driver?) — yielding the chip")
            time.sleep(30)
            waited += 30
        if not tunnel_up():
            log(f"tunnel dropped before {label}; pausing sequence")
            return
        argv, env = stage_argv(label, dict(env_over) if env_over else None)
        t0 = time.monotonic()
        out_path = os.path.join(out_dir, f"{label}.jsonl")
        err_path = os.path.join(out_dir, f"{label}.err")
        try:
            with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
                proc = subprocess.run(
                    argv, env=env, stdout=out_f, stderr=err_f, timeout=budget
                )
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            rc = "timeout"
        dt = time.monotonic() - t0
        got_lines = _useful_lines(out_path, label)
        log(f"stage {label}: rc={rc} {dt:.0f}s {got_lines} useful lines")
        # A stage that produced real data counts as done even on timeout;
        # anything else (zero useful lines) is retried in the next window.
        if got_lines:
            done.add(label)
    log("window sequence complete")


def _done_from_disk() -> set:
    """Stages already captured in ANY stamp dir under OUT_ROOT (useful
    lines present). Makes done-state restart-safe: a daemon restart (code
    update, crash + supervisor relaunch) resumes at the first uncaptured
    stage instead of burning window time re-measuring what's on disk."""
    done: set = set()
    try:
        stamps = sorted(os.listdir(OUT_ROOT))
    except OSError:
        return done
    for stamp in stamps:
        for label, _, _ in STAGES:
            path = os.path.join(OUT_ROOT, stamp, f"{label}.jsonl")
            if label not in done and _useful_lines(path, label):
                done.add(label)
    return done


def main() -> None:
    os.makedirs(OUT_ROOT, exist_ok=True)
    done: set = _done_from_disk()
    if done:
        log(f"resume: {len(done)} stages already captured on disk "
            f"({', '.join(sorted(done))})")
    log(f"autorun start (poll {POLL_S:.0f}s, stages={len(STAGES)})")
    while True:
        # A foreign bench (the driver's round-end run) owns both the chip
        # AND the host CPUs: even the poll probe's jax import measurably
        # inflates its CPU-side submit-latency fleet. Defer entirely.
        if _foreign_bench_running():
            log("foreign bench running — poll deferred")
            time.sleep(POLL_S)
            continue
        if tunnel_up():
            log("UP" + (" (all stages done)" if all(
                label in done for label, _, _ in STAGES) else ""))
            run_window(done)
        else:
            log("DOWN")
        time.sleep(POLL_S)


if __name__ == "__main__":
    main()
