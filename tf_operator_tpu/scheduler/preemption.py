"""Preemption: make room for a higher-priority gang, whole gangs at a time.

Victim selection policy (the ISSUE's contract):

- only gangs with STRICTLY lower priority than the pending gang are
  candidates — equal-priority work is never preempted (that way churn
  lies; aging would see-saw two equal gangs forever),
- candidates are considered lowest-priority first, youngest first within
  a priority (the cheapest work to redo is the work that has run the
  shortest time),
- the chosen set is minimal: after the greedy sweep finds a feasible
  set, every victim that can be returned without breaking feasibility is
  returned (greedy-then-prune; victims are whole gangs, so "minimal"
  means no removable member, not globally optimal bin packing).

Victims are evicted as gangs — checkpoint-signaled, every pod deleted,
capacity refunded — and requeued as gangs with their original enqueue
time, so a preempted gang keeps its aging credit and re-admits ahead of
later arrivals of its class instead of restarting at the back of the
line.
"""

from __future__ import annotations

from tf_operator_tpu.scheduler.gang import Gang
from tf_operator_tpu.scheduler.placement import TopologyPlacer
from tf_operator_tpu.scheduler.queue import QuotaLedger


def _feasible_with(
    pending: Gang,
    victims: list[Gang],
    placer: TopologyPlacer,
    ledger: QuotaLedger,
) -> bool:
    """Would releasing ``victims`` let ``pending`` place AND pass quota?"""
    # Simulate the placer with the victims' cells freed.
    sim = TopologyPlacer(placer.capacity)
    sim._used = {gen: set(cells) for gen, cells in placer._used.items()}
    for v in victims:
        sim.release(v.placements)
    if sim.try_fit(pending.slices) is None:
        return False
    # Simulate the ledger with the victims refunded.
    sim_ledger = QuotaLedger(ledger.quotas)
    sim_ledger._chips = dict(ledger._chips)
    sim_ledger._slices = dict(ledger._slices)
    for v in victims:
        sim_ledger.refund(v)
    return sim_ledger.fits(pending)


def select_victims(
    pending: Gang,
    admitted: list[Gang],
    placer: TopologyPlacer,
    ledger: QuotaLedger,
) -> list[Gang] | None:
    """Minimal set of strictly-lower-priority gangs whose eviction lets
    ``pending`` admit; None when no such set exists — or when no eviction
    is needed at all (pending fits free capacity; that case belongs to the
    admit path, which the pump's head-of-line discipline governs, and must
    never be reached by pointlessly evicting someone)."""
    if _feasible_with(pending, [], placer, ledger):
        return None
    # no_preempt gangs (serve replicas mid-drain, gang.py) are not
    # candidates at any priority: their chips are already being
    # released via the bounded drain, and evicting them on top would
    # drop the admitted requests the drain exists to finish.
    candidates = [
        g for g in admitted
        if g.priority < pending.priority and not g.no_preempt
    ]
    if not candidates:
        return None
    # Lowest priority first; youngest (latest admission) first within it.
    candidates.sort(key=lambda g: (g.priority, -(g.admitted_at or 0.0)))

    chosen: list[Gang] = []
    for g in candidates:
        chosen.append(g)
        if _feasible_with(pending, chosen, placer, ledger):
            break
    else:
        return None  # even evicting every candidate is not enough

    # Prune: drop any victim whose eviction turned out unnecessary (the
    # greedy sweep may have collected small gangs before the one whose
    # block actually frees the hole). Iterate oldest-priority-last so the
    # survivors stay the cheapest feasible choice.
    for g in list(chosen):
        trial = [v for v in chosen if v is not g]
        if trial and _feasible_with(pending, trial, placer, ledger):
            chosen = trial
    return chosen
