"""Gang model: the unit of all-or-nothing admission.

A *gang* is one TPUJob's complete pod set viewed as a single schedulable
object — the admission-level answer to the PDB-only ceiling of the
reference (jobcontroller.go:196-249 creates a disruption budget and hopes
an external gang scheduler honors it; pods are still admitted one-by-one).
Here no pod of a job may run before the whole job is admitted:

- pods are created with a K8s-style *scheduling gate*
  (``spec.schedulingGates: [{"name": "tpuflow.org/gang-admission"}]``);
  the cluster backends refuse to run a gated pod (memcluster raises
  Invalid on a Running status write, the wire stub returns 422),
- the scheduler admits the gang as a whole — capacity, quota and
  placement are reserved for EVERY slice pod before any pod is released,
- the admission decision is persisted on the job (annotations below), so
  a controller crash between "admitted" and "released" recovers by
  finishing the release, never by re-arbitrating a half-running slice.

Why partial allocation is worthless on TPU: a v5e-16 slice spans 4 hosts
wired by ICI; 3 of 4 workers running is not a smaller slice, it is a
deadlock (arXiv:2011.03641, arXiv:1909.09756 both key pod efficiency on
whole-slice, topology-contiguous placement).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

from tf_operator_tpu.api.types import TPUJob
from tf_operator_tpu.topology import slices as topo_slices

# The scheduling-gate name stamped on every gang pod at creation.
GATE_NAME = "tpuflow.org/gang-admission"

# Admission state persisted on the TPUJob (the recovery contract: the
# in-memory scheduler is authoritative while alive; annotations let a
# restarted controller rebuild the ledger without re-admitting blindly).
ANNOTATION_STATE = "scheduler.tpuflow.org/state"
ANNOTATION_ENQUEUED_AT = "scheduler.tpuflow.org/enqueued-at"
ANNOTATION_ADMITTED_AT = "scheduler.tpuflow.org/admitted-at"
ANNOTATION_PLACEMENTS = "scheduler.tpuflow.org/placements"
ANNOTATION_PREEMPTED_AT = "scheduler.tpuflow.org/preempted-at"
ANNOTATION_CHIPS = "scheduler.tpuflow.org/chips"
# Stamped (alongside preempted-at — the same checkpoint-signal contract)
# when the fleet-health layer evicts a gang off draining/cordoned cells;
# the controller keys the JobMigrating condition on it (health/monitor.py).
ANNOTATION_MIGRATED_AT = "health.tpuflow.org/migrated-at"
# Stamped by the fleet-serving controller (fleet/controller.py) on a
# serve replica's child job when its bounded SIGTERM drain begins
# (scale-down / rolling update). A draining gang is mid-handoff — the
# router has deregistered it and admitted requests are finishing — so
# preemption must not evict it: the drain IS the eviction, already in
# flight, and a preemption on top would turn "zero dropped requests"
# into dropped requests. reconcile_gang re-reads it every sync.
ANNOTATION_DRAINING_AT = "fleet.tpuflow.org/draining-at"

STATE_QUEUED = "queued"
STATE_ADMITTED = "admitted"

# Priority-class table. K8s priority classes are cluster-defined names; this
# is the operator's built-in set. A numeric priorityClass string ("750") is
# honored verbatim, so users are not limited to the names below.
DEFAULT_PRIORITY_CLASSES: dict[str, int] = {
    "low": -100,
    "default": 0,
    "high": 100,
    "critical": 1000,
}


def resolve_priority(
    priority_class: str | None, table: dict[str, int] | None = None
) -> int:
    """Priority-class name → integer priority (higher = sooner)."""
    if not priority_class:
        return 0
    table = table if table is not None else DEFAULT_PRIORITY_CLASSES
    if priority_class in table:
        return table[priority_class]
    try:
        return int(priority_class)
    except ValueError:
        return 0


@dataclass(frozen=True)
class SliceRequest:
    """One contiguous block a gang needs: a slice's physical chip shape."""

    generation: str  # "v5e"
    dims: tuple[int, ...]  # (4, 4)
    chips: int  # 16


@dataclass
class Gang:
    """A job's pod set as one admission unit."""

    namespace: str
    name: str
    uid: str
    priority_class: str
    priority: int
    pod_count: int
    slices: list[SliceRequest] = field(default_factory=list)
    enqueued_at: float = field(default_factory=time.time)
    admitted_at: float | None = None
    requeues: int = 0
    state: str = STATE_QUEUED
    # Non-empty = this gang can NEVER admit under the configured fleet /
    # quota (unknown generation, block bigger than the mesh, request over
    # the namespace's absolute budget). The pump skips it so one
    # misconfigured job cannot wedge the strict head-of-line queue.
    infeasible: str = ""
    # True while a QUEUED gang still owns pods — an interrupted eviction
    # (preemption or migration crashed between the state=queued persist and
    # the deletion loop). The pump must not re-admit it until the leftovers
    # are gone: a fresh admission with live pods would resurrect the gang
    # IN PLACE on its old (possibly cordoned) cells while the ledger
    # charges the new placement. Cleared by the next reconcile that
    # observes zero pods.
    pending_cleanup: bool = False
    # Graceful-eviction barrier (ckpt coordination, scheduler/core.py):
    # set while the gang has been checkpoint-signaled (state=queued +
    # signal-gen + deadline persisted on the job) but its pods are HELD
    # until every pod acks the generation or the deadline passes. The gang
    # stays admitted in memory — capacity is only refunded once the
    # deletion loop actually runs. A successor controller recovers the
    # same barrier from the job annotations, not from these fields.
    evict_gen: int | None = None
    evict_deadline: float | None = None
    evict_signaled_at: float | None = None
    evict_credit: float = 0.0
    # True while the job carries ANNOTATION_DRAINING_AT (a serve replica
    # mid-drain): excluded from preemption victim selection — see the
    # annotation's comment. Refreshed from the job every reconcile_gang.
    no_preempt: bool = False
    # Filled at admission: one placement per SliceRequest (see placement.py).
    placements: list[Any] = field(default_factory=list)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def total_chips(self) -> int:
        return sum(s.chips for s in self.slices)

    @property
    def num_slices(self) -> int:
        return len(self.slices)


def gang_from_job(
    job: TPUJob, priority_table: dict[str, int] | None = None
) -> Gang:
    """Build the admission unit for a (defaulted) TPUJob.

    Every replica set bound to a TPU slice contributes ``num_slices``
    independent contiguous-block requests; replica sets without a slice
    binding contribute pods but no chips (they gate and release with the
    gang — a PS pod running against an unadmitted worker slice is just as
    wedged as a half slice).
    """
    slice_reqs: list[SliceRequest] = []
    pod_count = 0
    for spec in job.spec.replica_specs.values():
        pod_count += spec.replicas or 0
        if spec.tpu and spec.tpu.accelerator_type:
            topo = topo_slices.resolve(
                spec.tpu.accelerator_type, spec.tpu.topology
            )
            for _ in range(max(1, spec.tpu.num_slices)):
                slice_reqs.append(
                    SliceRequest(topo.generation, topo.dims, topo.num_chips)
                )
    pclass = job.spec.scheduling.priority_class or ""
    return Gang(
        namespace=job.metadata.namespace,
        name=job.metadata.name,
        uid=job.metadata.uid,
        priority_class=pclass,
        priority=resolve_priority(pclass, priority_table),
        pod_count=pod_count,
        slices=slice_reqs,
        no_preempt=ANNOTATION_DRAINING_AT in (job.metadata.annotations or {}),
    )


# ---------------------------------------------------------------------------
# Scheduling-gate helpers over unstructured pods
# ---------------------------------------------------------------------------

def scheduling_gates(pod: dict[str, Any]) -> list[str]:
    return [
        g.get("name", "")
        for g in pod.get("spec", {}).get("schedulingGates", []) or []
    ]


def is_gated(pod: dict[str, Any], gate: str = GATE_NAME) -> bool:
    return gate in scheduling_gates(pod)


def ungate_patch(pod: dict[str, Any], gate: str = GATE_NAME) -> dict[str, Any]:
    """Merge-patch body removing one gate while preserving any others
    (merge-patch replaces lists wholesale, so the remainder is sent back)."""
    remaining = [
        g
        for g in pod.get("spec", {}).get("schedulingGates", []) or []
        if g.get("name") != gate
    ]
    return {"spec": {"schedulingGates": remaining}}
