"""Topology-aware placement: contiguous-block fit on the physical mesh.

A TPU slice is not "N chips somewhere" — it is an axis-aligned contiguous
block of the pod's ICI torus (topology/slices.py resolves "v5e-16" to a
4x4 block). The placer models each generation's installed capacity as a
d-dimensional mesh of unit chips and answers the only question gang
admission needs: *does this gang's full set of slice blocks fit in the
free cells right now, and where?*

Design notes:

- Fit is all-or-nothing across a gang's slices (a multislice job's DCN
  halves are placed together or not at all), mirroring the whole-slice
  placement result of arXiv:2011.03641 / arXiv:1909.09756.
- Blocks may be rotated (any axis permutation of the requested dims): a
  4x2 request fits a 2x4 hole — the ICI fabric is symmetric per axis at
  this granularity.
- No torus wrap-around: blocks are contiguous in the untorn mesh, the
  conservative reading of "contiguous" (GKE's TPU placement behaves the
  same way for sub-pod slices).
- ``capacity=None`` means an unbounded virtual fleet: every request fits
  with a zero-footprint placement. This is the default wiring so the
  scheduler pipeline (gate → admit → release) runs everywhere, while
  capacity arbitration only engages when the operator declares a fleet
  (--tpu-capacity).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Iterable

from tf_operator_tpu.scheduler.gang import SliceRequest
from tf_operator_tpu.topology import slices as topo_slices


class CapacityError(ValueError):
    """A request that can NEVER fit (unknown generation / bigger than the
    whole mesh) — distinct from "does not fit right now"."""


@dataclass(frozen=True)
class Placement:
    """One slice's assigned block: generation + offset + (rotated) dims."""

    generation: str
    offset: tuple[int, ...]
    dims: tuple[int, ...]

    def cells(self) -> Iterable[tuple[int, ...]]:
        ranges = [range(o, o + d) for o, d in zip(self.offset, self.dims)]
        return itertools.product(*ranges)

    @property
    def chips(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "offset": list(self.offset),
            "dims": list(self.dims),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Placement":
        return cls(
            generation=d["generation"],
            offset=tuple(int(x) for x in d["offset"]),
            dims=tuple(int(x) for x in d["dims"]),
        )


def parse_capacity(spec: str) -> dict[str, tuple[int, ...]]:
    """Parse the operator flag form: ``"v5e=16x16,v4=4x4x8"``."""
    out: dict[str, tuple[int, ...]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        gen, _, dims = part.partition("=")
        gen = gen.strip().lower()
        if gen not in topo_slices.GENERATIONS:
            raise CapacityError(
                f"unknown TPU generation {gen!r} in capacity spec "
                f"(known: {sorted(topo_slices.GENERATIONS)})"
            )
        out[gen] = topo_slices.parse_topology(dims)
    return out


class TopologyPlacer:
    """Tracks free/used cells per generation mesh; finds contiguous blocks.

    Not thread-safe on its own — the GangScheduler serializes access.
    """

    def __init__(self, capacity: dict[str, tuple[int, ...]] | None = None):
        self.capacity = dict(capacity) if capacity is not None else None
        self._used: dict[str, set[tuple[int, ...]]] = {
            gen: set() for gen in (self.capacity or {})
        }
        # Cells withdrawn from service by the fleet-health layer
        # (health/monitor.py): excluded from every fit, but NOT from
        # feasibility (fits_empty) — a cordon is temporary, and
        # "infeasible" is forever. Disjoint bookkeeping from _used: a
        # cordoned cell may simultaneously be occupied by a gang that has
        # not been migrated off it yet.
        self._cordoned: dict[str, set[tuple[int, ...]]] = {}

    @property
    def unbounded(self) -> bool:
        return self.capacity is None

    # -- queries -------------------------------------------------------------

    def chips_total(self) -> dict[str, int]:
        if self.capacity is None:
            return {}
        out = {}
        for gen, mesh in self.capacity.items():
            n = 1
            for d in mesh:
                n *= d
            out[gen] = n
        return out

    def chips_in_use(self) -> dict[str, int]:
        return {gen: len(cells) for gen, cells in self._used.items()}

    def chips_cordoned(self) -> dict[str, int]:
        return {
            gen: len(cells)
            for gen, cells in self._cordoned.items()
            if cells
        }

    def fits_empty(self, req: SliceRequest) -> bool:
        """Could this block EVER place on an idle fleet? False means the
        request is permanently infeasible (generation not installed, or
        bigger than the whole mesh) — the CapacityError class of failure,
        as opposed to "does not fit right now". Cordons are deliberately
        ignored: a fully-cordoned mesh heals, an unknown generation never
        does."""
        if self.capacity is None:
            return True
        return self._find(req, set(), avoid_cordoned=False) is not None

    # -- cordons (fleet-health integration) ----------------------------------

    def cordon(
        self, generation: str, cells: Iterable[tuple[int, ...]]
    ) -> None:
        """Withdraw cells from placement. Idempotent; unknown generations
        are tracked too (harmless — they can never be placed on anyway)."""
        self._cordoned.setdefault(generation, set()).update(
            tuple(int(x) for x in c) for c in cells
        )

    def uncordon(
        self, generation: str, cells: Iterable[tuple[int, ...]]
    ) -> None:
        pool = self._cordoned.get(generation)
        if pool:
            pool.difference_update(tuple(int(x) for x in c) for c in cells)

    def cordoned(self) -> dict[str, set[tuple[int, ...]]]:
        """View of the cordoned cells (copy; per-generation)."""
        return {
            gen: set(cells)
            for gen, cells in self._cordoned.items()
            if cells
        }

    def is_cordoned(self, generation: str, cell: tuple[int, ...]) -> bool:
        return tuple(cell) in self._cordoned.get(generation, ())

    # -- fit -----------------------------------------------------------------

    def try_fit(
        self, requests: list[SliceRequest]
    ) -> list[Placement] | None:
        """All-or-nothing tentative fit; returns placements without
        committing them, or None when any block has no home right now."""
        if self.capacity is None:
            return [
                Placement(r.generation, (), ()) for r in requests
            ]
        # Place the largest blocks first: greedy first-fit with big-first
        # ordering avoids the easy fragmentation traps (two 2x2s straddling
        # the only 4x4 hole).
        order = sorted(
            range(len(requests)), key=lambda i: -requests[i].chips
        )
        tentative: dict[str, set[tuple[int, ...]]] = {
            gen: set(cells) for gen, cells in self._used.items()
        }
        placed: dict[int, Placement] = {}
        for i in order:
            req = requests[i]
            spot = self._find(req, tentative.get(req.generation))
            if spot is None:
                return None
            placed[i] = spot
            tentative.setdefault(req.generation, set()).update(spot.cells())
        return [placed[i] for i in range(len(requests))]

    def _find(
        self,
        req: SliceRequest,
        used: set[tuple[int, ...]] | None,
        avoid_cordoned: bool = True,
    ) -> Placement | None:
        mesh = (self.capacity or {}).get(req.generation)
        if mesh is None:
            return None  # generation not installed in this fleet
        dims = tuple(req.dims)
        if len(dims) > len(mesh):
            # A 3D request cannot embed in a 2D mesh unless the extra
            # dims are singleton.
            if any(d != 1 for d in dims[len(mesh):]):
                return None
            dims = dims[: len(mesh)]
        # Pad to mesh rank so rotation covers every axis assignment.
        dims = dims + (1,) * (len(mesh) - len(dims))
        used = used or set()
        if avoid_cordoned:
            cordoned = self._cordoned.get(req.generation)
            if cordoned:
                used = used | cordoned
        seen: set[tuple[int, ...]] = set()
        for perm in itertools.permutations(dims):
            if perm in seen:
                continue
            seen.add(perm)
            if any(p > m for p, m in zip(perm, mesh)):
                continue
            for offset in itertools.product(
                *[range(m - p + 1) for p, m in zip(perm, mesh)]
            ):
                candidate = Placement(req.generation, offset, perm)
                if not any(c in used for c in candidate.cells()):
                    return candidate
        return None

    # -- commit/release ------------------------------------------------------

    def commit(self, placements: list[Placement]) -> None:
        if self.capacity is None:
            return
        for p in placements:
            self._used.setdefault(p.generation, set()).update(p.cells())

    def release(self, placements: list[Placement]) -> None:
        if self.capacity is None:
            return
        for p in placements:
            cells = self._used.get(p.generation)
            if cells:
                cells.difference_update(p.cells())
