"""GangScheduler: the cluster-level admission authority.

Sits between job reconciliation and pod creation. The controller asks it
three questions per sync:

- ``reconcile_gang(job)``: is this job's gang admitted? (registers new
  gangs, recovers persisted decisions after a controller restart, and
  pumps the queue — admitting / preempting as capacity allows);
- ``release_gang(job)``: every slice pod now exists — atomically lift
  the scheduling gates so the whole gang becomes runnable at once;
- ``release_job(key)``: the job is terminal or deleted — refund its
  capacity and quota and forget the gang.

Crash consistency: the admission decision is persisted on the job
(annotations in gang.py) BEFORE any gate is lifted. A controller dying
anywhere in the pipeline leaves one of two recoverable worlds: gang not
admitted (all pods gated — the backends refuse to run them) or admitted
(recovery re-reads the annotation, recharges the ledger from the
persisted placements, and finishes the release). There is no world in
which a strict subset of a slice can run while the rest cannot.

The in-memory queue/ledger are authoritative while the scheduler lives;
annotations exist for recovery, the CLI (`tpuctl queue`), and operators
reading raw job objects.

Lock scope: release/evict perform store I/O while holding the scheduler
lock. That serializes concurrent syncs against one slow apiserver call —
accepted for now because arbitration correctness depends on the ledger
not changing between fit-check and commit, the controller's sync loop is
already serialized per key, and the steady-state release relist is
skipped at the call site (reconcile_job only re-enters release_gang while
gated or missing pods are visible). Moving the wire calls outside the
lock (decide under lock, act outside, re-validate on re-entry) is the
known next step if multi-sync threadiness lands.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import TPUJob
from tf_operator_tpu.ckpt import protocol as ckpt_protocol
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ApiError, ClusterClient, NotFound
from tf_operator_tpu.runtime.metrics import (
    CKPT_BARRIER_SECONDS,
    CKPT_SIGNALS_TOTAL,
    HEALTH_MIGRATIONS_TOTAL,
    SCHED_ADMISSION_SECONDS,
    SCHED_ADMISSIONS_TOTAL,
    SCHED_ADMITTED_GANGS,
    SCHED_CHIPS_IN_USE,
    SCHED_PREEMPTIONS_TOTAL,
    SCHED_QUEUE_DEPTH,
    SCHED_RELEASES_TOTAL,
)
from tf_operator_tpu.scheduler.gang import (
    ANNOTATION_ADMITTED_AT,
    ANNOTATION_CHIPS,
    ANNOTATION_DRAINING_AT,
    ANNOTATION_ENQUEUED_AT,
    ANNOTATION_MIGRATED_AT,
    ANNOTATION_PLACEMENTS,
    ANNOTATION_PREEMPTED_AT,
    ANNOTATION_STATE,
    DEFAULT_PRIORITY_CLASSES,
    GATE_NAME,
    STATE_ADMITTED,
    STATE_QUEUED,
    Gang,
    gang_from_job,
    is_gated,
    ungate_patch,
)
from tf_operator_tpu.scheduler.placement import Placement, TopologyPlacer
from tf_operator_tpu.scheduler.preemption import select_victims
from tf_operator_tpu.scheduler.queue import AdmissionQueue, Quota, QuotaLedger
from tf_operator_tpu.utils import logger

EVENT_GANG_QUEUED = "GangQueued"
EVENT_GANG_ADMITTED = "GangAdmitted"
EVENT_GANG_RELEASED = "GangReleased"
EVENT_PREEMPTED = "GangPreempted"
EVENT_UNSCHEDULABLE = "GangUnschedulable"
EVENT_MIGRATING = "JobMigrating"
EVENT_CKPT_ACKED = "CheckpointAcked"
EVENT_CKPT_SKIPPED = "CheckpointSkipped"

# _evict outcomes. FAILED: nothing changed, victim keeps capacity — retry.
# SIGNALED: the graceful-eviction barrier just started (queued state +
# signal persisted, pods signaled but HELD). PENDING: a barrier is already
# in flight and cannot complete yet. DONE: pods deleted, capacity
# refunded, gang requeued.
EVICT_FAILED = "failed"
EVICT_SIGNALED = "signaled"
EVICT_PENDING = "pending"
EVICT_DONE = "done"


@dataclass
class SchedulerConfig:
    # Installed fleet per generation, e.g. {"v5e": (16, 16)}. None =
    # unbounded virtual fleet: every gang admits immediately (the gate →
    # admit → release pipeline still runs, so partial-slice protection
    # holds even without declared capacity).
    capacity: dict[str, tuple[int, ...]] | None = None
    quotas: dict[str, Quota] = field(default_factory=dict)
    priority_classes: dict[str, int] = field(
        default_factory=lambda: dict(DEFAULT_PRIORITY_CLASSES)
    )
    aging_rate: float = 1.0
    preemption: bool = True
    # Stamp the admission gate on created pods. Off = legacy pass-through
    # behavior (pods run as soon as a kubelet picks them up).
    gate_pods: bool = True
    # Aging seconds granted to a gang evicted by the fleet-health layer
    # (on top of its retained enqueue time): a migration is the cluster's
    # fault, not the tenant's, so the migrated gang out-bids same-class
    # arrivals when re-placement has to wait for capacity.
    migration_credit: float = 60.0
    # Graceful-eviction barrier (ckpt coordination): seconds an eviction
    # waits between the checkpoint signal and the pod deletions, released
    # early the moment every gang pod acks the signal. 0 (the default, and
    # the pre-barrier behavior every existing test encodes) evicts in the
    # same pass — signal and delete with no wait; the operator main wires
    # a production default via --checkpoint-grace. Requires a
    # CheckpointRegistry attached (self.ckpt) to take effect.
    checkpoint_grace: float = 0.0


@dataclass
class AdmissionDecision:
    admitted: bool
    state: str
    reason: str = ""
    # True while the gang is admitted but checkpoint-signaled and awaiting
    # its ack/deadline (the graceful-eviction barrier).
    evicting: bool = False
    # Ask the controller to re-sync this key after this many seconds (the
    # barrier's deadline expiry must not wait for the periodic resync).
    requeue_after: float | None = None


class GangScheduler:
    def __init__(
        self,
        client: ClusterClient | None = None,
        config: SchedulerConfig | None = None,
        recorder: Any | None = None,
    ) -> None:
        self.client = client
        self.config = config or SchedulerConfig()
        self.recorder = recorder
        self._lock = threading.RLock()
        self.queue = AdmissionQueue(self.config.aging_rate)
        self.placer = TopologyPlacer(self.config.capacity)
        self.ledger = QuotaLedger(self.config.quotas)
        self._admitted: dict[str, Gang] = {}
        self._wakeup: Callable[[str], None] | None = None
        # Shared pod informer (controller-owned), when one was attached:
        # gang pod enumeration (release relist, eviction work-list) reads
        # this cache instead of issuing an API LIST per call — the
        # steady-state pump then costs zero API round-trips.
        self._pod_lister: Any | None = None
        # Set by health/monitor.py when a FleetHealthMonitor is wired in;
        # the controller reaches the monitor through this back-reference.
        # The scheduler itself never calls into it (lock ordering: the
        # monitor's lock is always taken before this one, never after).
        self.health: Any | None = None
        # Set by ckpt/registry.py when a CheckpointRegistry is wired in:
        # the eviction barrier's ack source (barrier_acked) and the skip
        # marker sink. Lock ordering: this scheduler's lock may be held
        # when calling into the registry; the registry never calls back.
        self.ckpt: Any | None = None
        self.log = logger.with_fields(component="gang-scheduler")

    # -- wiring --------------------------------------------------------------

    def attach(
        self,
        client: ClusterClient,
        recorder: Any | None = None,
        wakeup: Callable[[str], None] | None = None,
        pod_lister: Any | None = None,
    ) -> None:
        """Late binding for pieces the controller owns (operator.py builds
        the scheduler from flags before any client exists)."""
        if self.client is None:
            self.client = client
        if self.recorder is None:
            self.recorder = recorder
        if wakeup is not None:
            self._wakeup = wakeup
        if pod_lister is not None:
            self._pod_lister = pod_lister

    def _list_gang_pods(self, gang: Gang) -> list[dict[str, Any]]:
        """This gang's pods, from the shared informer cache when possible.

        Falls back to an API LIST only when the cache cannot be
        authoritative yet: not attached / not synced, or showing fewer
        pods than the gang expects (a creation is still in flight — the
        same sync that created the pods asks for the release relist
        before the watch deltas land, and gang release must not wait a
        round-trip of informer lag). In steady state — every pod exists
        and is cached — this is a pure index lookup.
        """
        selector = {constants.LABEL_JOB_NAME: gang.name}
        lister = self._pod_lister
        if lister is not None and lister.has_synced():
            pods = lister.list(gang.namespace, selector)
            if len(pods) >= gang.pod_count:
                return pods
        assert self.client is not None
        return self.client.list(objects.PODS, gang.namespace, selector)

    def gates_for(self, job: TPUJob) -> list[dict[str, str]]:
        """Scheduling gates to stamp on this job's pods at creation."""
        if not self.config.gate_pods:
            return []
        return [{"name": GATE_NAME}]

    # -- controller-facing surface -------------------------------------------

    def reconcile_gang(self, job: TPUJob, has_pods: bool = False) -> AdmissionDecision:
        """Register/recover this job's gang, pump the queue, and report
        whether the gang currently holds an admission."""
        with self._lock:
            key = job.key
            gang = self._admitted.get(key) or self.queue.get(key)
            if gang is not None and job.metadata.uid and gang.uid and (
                gang.uid != job.metadata.uid
            ):
                # Same name, new job incarnation: retire the stale gang.
                self._forget(gang)
                gang = None
            if gang is None:
                gang = self._register(job, has_pods)
            # Serve replicas mid-drain (fleet/controller.py stamped the
            # draining annotation) are preemption-exempt: the drain IS
            # the eviction, already in flight — re-read every sync so
            # the exemption appears when the drain begins and never
            # outlives the job object that carried it.
            gang.no_preempt = ANNOTATION_DRAINING_AT in (
                job.metadata.annotations or {}
            )
            if gang.state == STATE_ADMITTED and self._on_cordoned_cells(gang):
                # Fleet health cordoned cells under this gang (possibly in a
                # previous controller incarnation — the cordon outlives us
                # via the health monitor's persisted record, while the gang
                # was just recovered as admitted). Migrate: checkpoint-
                # signal, evict whole, requeue with aging credit. If the
                # eviction cannot be persisted the gang simply stays
                # admitted on its cells until the next sync retries.
                self._migrate_locked(gang)
            if gang.state == STATE_ADMITTED and gang.evict_deadline is not None:
                # Graceful-eviction barrier in flight (preemption or
                # migration): complete it the moment every pod acked the
                # signal or the grace deadline passed; until then the gang
                # keeps its pods and the controller re-syncs at expiry.
                if self._finish_evict_locked(gang) == EVICT_PENDING:
                    self._export_gauges()
                    return AdmissionDecision(
                        admitted=True,
                        state=gang.state,
                        evicting=True,
                        requeue_after=max(
                            0.05, gang.evict_deadline - time.time()
                        ),
                    )
            if gang.state != STATE_ADMITTED:
                # Interrupted-eviction guard: a queued gang that still owns
                # pods must not re-admit until the controller's cleanup
                # deleted them (see Gang.pending_cleanup). Recomputed from
                # the caller's live observation each sync, so it clears the
                # moment the leftovers are gone.
                gang.pending_cleanup = has_pods
                self._pump()
            self._export_gauges()
            admitted = gang.state == STATE_ADMITTED
            return AdmissionDecision(
                admitted=admitted,
                state=gang.state,
                reason="" if admitted else "waiting for capacity",
            )

    def release_gang(self, job: TPUJob) -> bool:
        """Atomically lift the gates once EVERY expected pod exists.

        Called after pod reconciliation; returns True when the gang is
        fully released (no gated pods remain). The all-pods-first check is
        what makes release all-or-nothing: a gang is never part-runnable
        because creation is still in flight.
        """
        with self._lock:
            gang = self._admitted.get(job.key)
            if gang is None:
                return False
            pods = self._list_gang_pods(gang)
            if len(pods) < gang.pod_count:
                return False
            gated = [p for p in pods if is_gated(p)]
            if not gated:
                return True
            names = [objects.name_of(p) for p in gated]
            ungate_bulk = getattr(self.client, "ungate_pods", None)
            if callable(ungate_bulk):
                # One store transaction: the whole gang becomes runnable
                # in a single resource-version tick (memcluster backend).
                ungate_bulk(gang.namespace, names, GATE_NAME)
            else:
                # Wire backends (real apiserver) have no multi-object
                # transaction; the admission annotation was persisted
                # before this point, so a crash mid-loop is finished by
                # recovery, never re-arbitrated.
                for p in gated:
                    try:
                        self.client.patch_merge(
                            objects.PODS,
                            gang.namespace,
                            objects.name_of(p),
                            ungate_patch(p),
                        )
                    except NotFound:
                        continue
            SCHED_RELEASES_TOTAL.inc(len(gated))
            self._event(
                gang, EVENT_GANG_RELEASED,
                f"released {len(gated)} gated pod(s); gang is runnable",
                warning=False,
            )
            return True

    def release_job(self, key: str) -> None:
        """Terminal or deleted job: refund capacity/quota, forget the gang,
        and re-pump (freed chips may admit the next gang in line)."""
        with self._lock:
            gang = self._admitted.get(key) or self.queue.get(key)
            if gang is None:
                return
            self._forget(gang)
            self._pump()
            self._export_gauges()

    # -- fleet-health surface (health/monitor.py) -----------------------------

    def cordon_cells(
        self, generation: str, cells: list[tuple[int, ...]]
    ) -> list[str]:
        """Withdraw cells from placement. Returns the keys of admitted
        gangs now sitting on cordoned cells — the migration work-list the
        health monitor drives AFTER persisting the cordon (crash between
        persist and migration is finished by recovery + the reconcile-time
        cordon check in reconcile_gang)."""
        with self._lock:
            self.placer.cordon(generation, cells)
            return sorted(
                g.key
                for g in self._admitted.values()
                if self._on_cordoned_cells(g)
            )

    def uncordon_cells(
        self, generation: str, cells: list[tuple[int, ...]]
    ) -> None:
        """Return cells to service and re-pump: the healed capacity may
        admit queued gangs immediately."""
        with self._lock:
            self.placer.uncordon(generation, cells)
            self._pump()
            self._export_gauges()

    def gangs_on_cordoned_cells(self) -> list[str]:
        with self._lock:
            return sorted(
                g.key
                for g in self._admitted.values()
                if self._on_cordoned_cells(g)
            )

    def migrate_gang(self, key: str, reason: str = "cell cordoned") -> bool:
        """Maintenance-aware migration: checkpoint-signal the gang, evict
        it WHOLE off its (draining/cordoned) cells, requeue it with an
        aging credit, and immediately try to re-place it on healthy cells.
        Same crash discipline as preemption: the queued state (+ migrated-at
        marker) is persisted on the job before any pod dies."""
        with self._lock:
            gang = self._admitted.get(key)
            if gang is None:
                return False
            return self._migrate_locked(gang)

    def placements_of(self, key: str) -> list[Placement]:
        """The admitted gang's placements ([] when not admitted) — the
        cell-attribution lookup the health monitor scores exit reports
        against."""
        with self._lock:
            gang = self._admitted.get(key)
            return list(gang.placements) if gang is not None else []

    def _on_cordoned_cells(self, gang: Gang) -> bool:
        for p in gang.placements:
            for cell in p.cells():
                if self.placer.is_cordoned(p.generation, cell):
                    return True
        return False

    def _migrate_locked(self, gang: Gang) -> bool:
        already_evicting = gang.evict_deadline is not None
        now = objects.now_iso()
        result = self._evict(
            gang,
            annotations={
                # preempted-at IS the checkpoint signal contract of PR 1 —
                # checkpoint-aware workloads watch for exactly this key;
                # migrated-at attributes the eviction to fleet health and
                # keys the JobMigrating condition.
                ANNOTATION_PREEMPTED_AT: now,
                ANNOTATION_MIGRATED_AT: now,
                ANNOTATION_STATE: STATE_QUEUED,
            },
            event=EVENT_MIGRATING,
            message=(
                "slice cells are draining/cordoned; checkpoint now — the "
                "gang will be re-placed whole on healthy cells"
            ),
            aging_credit=self.config.migration_credit,
            reason="migration",
        )
        if result == EVICT_FAILED:
            return False
        if not already_evicting:
            # Count the migration once, when it starts — whether it ran to
            # completion in one pass (no grace) or just signaled the
            # barrier. Re-entries while the barrier is pending land in the
            # EVICT_PENDING/EVICT_DONE branch above without re-counting.
            HEALTH_MIGRATIONS_TOTAL.inc()
        if result == EVICT_DONE:
            self._pump()
            self._export_gauges()
        return True

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """JSON-friendly view for /debug/scheduler and tests."""
        with self._lock:
            now = time.time()
            return {
                "capacity": {
                    gen: list(dims)
                    for gen, dims in (self.config.capacity or {}).items()
                } or None,
                "chipsInUse": self.placer.chips_in_use(),
                "chipsTotal": self.placer.chips_total(),
                "chipsCordoned": self.placer.chips_cordoned(),
                "cordonedCells": {
                    gen: sorted(list(c) for c in cells)
                    for gen, cells in self.placer.cordoned().items()
                },
                "quotaUsage": self.ledger.usage(),
                "admitted": [
                    self._gang_view(g, now)
                    for g in sorted(
                        self._admitted.values(), key=lambda g: g.key
                    )
                ],
                "queued": [
                    self._gang_view(g, now) for g in self.queue.ordered(now)
                ],
            }

    def _gang_view(self, g: Gang, now: float) -> dict[str, Any]:
        view = {
            "key": g.key,
            "state": g.state,
            "priorityClass": g.priority_class or "default",
            "priority": g.priority,
            "chips": g.total_chips,
            "slices": g.num_slices,
            "pods": g.pod_count,
            "requeues": g.requeues,
            "waitedSeconds": round(max(0.0, now - g.enqueued_at), 3),
        }
        if g.state == STATE_QUEUED:
            view["effectivePriority"] = round(
                self.queue.effective_priority(g, now), 3
            )
        if g.infeasible:
            view["infeasible"] = g.infeasible
        if g.evict_deadline is not None:
            view["evicting"] = {
                "signalGen": g.evict_gen,
                "graceRemaining": round(max(0.0, g.evict_deadline - now), 3),
            }
        return view

    # -- internals (lock held) -----------------------------------------------

    def _register(self, job: TPUJob, has_pods: bool) -> Gang:
        gang = gang_from_job(job, self.config.priority_classes)
        ann = job.metadata.annotations or {}
        if ann.get(ANNOTATION_STATE) == STATE_ADMITTED:
            # Recover a persisted admission (controller restart / failover):
            # recharge the ledger from the recorded placements so the new
            # incarnation arbitrates against true free capacity.
            self._recover_admitted(gang, ann)
            return gang
        if has_pods and ANNOTATION_STATE not in ann:
            # Grandfather: pods predate the scheduler (upgrade path). A
            # running job is never queued retroactively — admit in place,
            # overcommitting if its blocks no longer fit on paper.
            placements = self.placer.try_fit(gang.slices) or []
            self._admit_in_place(gang, placements)
            return gang
        # Fresh (or previously queued) gang: enqueue, preserving the
        # original enqueue time across controller restarts so aging credit
        # survives.
        enq = _parse_epoch(ann.get(ANNOTATION_ENQUEUED_AT))
        if enq is not None:
            gang.enqueued_at = enq
        gang.infeasible = self._infeasibility(gang)
        if gang.infeasible:
            self._event(
                gang, EVENT_UNSCHEDULABLE,
                f"gang can never admit: {gang.infeasible}", warning=True,
            )
        self.queue.add(gang)
        if ann.get(ANNOTATION_STATE) != STATE_QUEUED:
            self._persist(
                job.metadata.namespace, job.metadata.name,
                {
                    ANNOTATION_STATE: STATE_QUEUED,
                    ANNOTATION_ENQUEUED_AT: _fmt_epoch(gang.enqueued_at),
                    ANNOTATION_CHIPS: str(gang.total_chips),
                },
                typed=job,
            )
            self._event(
                gang, EVENT_GANG_QUEUED,
                f"gang queued for admission ({gang.pod_count} pod(s), "
                f"{gang.total_chips} chip(s), "
                f"priority {gang.priority_class or 'default'})",
                warning=False,
            )
        return gang

    def _recover_admitted(self, gang: Gang, ann: dict[str, str]) -> None:
        placements: list[Placement] = []
        try:
            placements = [
                Placement.from_dict(d)
                for d in json.loads(ann.get(ANNOTATION_PLACEMENTS, "[]"))
            ]
        except (ValueError, KeyError, TypeError):
            placements = []
        if not placements and not self.placer.unbounded and gang.slices:
            # Placements were not recorded (or capacity layout changed):
            # re-fit if possible, else recover overcommitted — an admitted
            # gang is never demoted by a controller restart.
            placements = self.placer.try_fit(gang.slices) or []
        enq = _parse_epoch(ann.get(ANNOTATION_ENQUEUED_AT))
        if enq is not None:
            gang.enqueued_at = enq
        gang.admitted_at = _parse_epoch(ann.get(ANNOTATION_ADMITTED_AT)) or time.time()
        gang.state = STATE_ADMITTED
        gang.placements = placements
        self.placer.commit(placements)
        self.ledger.charge(gang)
        self._admitted[gang.key] = gang

    def _admit_in_place(self, gang: Gang, placements: list[Placement]) -> None:
        gang.state = STATE_ADMITTED
        gang.admitted_at = time.time()
        gang.placements = placements
        self.placer.commit(placements)
        self.ledger.charge(gang)
        self._admitted[gang.key] = gang
        self._persist_admitted(gang)

    def _infeasibility(self, gang: Gang) -> str:
        """Why this gang can NEVER admit under the configured fleet/quota
        ("" = feasible). Checked once at registration: capacity and quotas
        are fixed for the scheduler's lifetime, so "never" is forever."""
        for req in gang.slices:
            if not self.placer.fits_empty(req):
                mesh = (self.config.capacity or {}).get(req.generation)
                return (
                    f"slice {req.generation} {'x'.join(map(str, req.dims))} "
                    + (
                        f"cannot fit the {'x'.join(map(str, mesh))} mesh"
                        if mesh is not None
                        else "targets a generation not in the declared fleet"
                    )
                )
        if not self.ledger.fits_ever(gang):
            return (
                f"request ({gang.total_chips} chip(s), {gang.num_slices} "
                f"slice(s)) exceeds namespace {gang.namespace!r}'s whole quota"
            )
        return ""

    def _pump(self) -> None:
        """Serve the queue in effective-priority order.

        Head-of-line is strict for FREE capacity: once a gang cannot be
        placed, no later gang may take free chips (backfill would starve
        the large slices gang admission exists for — the head keeps first
        claim on whatever frees up). But later gangs may still be served
        by PREEMPTION: eviction brings its own capacity, taken from
        strictly-lower-static-priority victims the blocked head, having
        already failed its own preemption attempt, could not claim. Without
        this, an aged low-priority head that can neither place nor preempt
        (aging raises queue position, never eviction rights — cross-class
        eviction by aging would see-saw with the requeued victim's retained
        aging credit) would wedge a preemption-capable critical gang behind
        it indefinitely. Permanently infeasible gangs are passed over
        entirely — one misconfigured job must not starve the cluster."""
        now = time.time()
        blocked = False
        for gang in self.queue.ordered(now):
            if gang.infeasible or gang.pending_cleanup:
                # Infeasible gangs can never admit; pending_cleanup gangs
                # must not admit YET (their interrupted-eviction leftovers
                # are still being deleted). Neither may wedge the head.
                continue
            if not blocked and self._try_admit(gang, now):
                continue
            if self.config.preemption and self._try_preempt_for(gang, now):
                continue
            blocked = True

    def _try_admit(self, gang: Gang, now: float) -> bool:
        if not self.ledger.fits(gang):
            return False
        placements = self.placer.try_fit(gang.slices)
        if placements is None:
            return False
        # Persist BEFORE committing any in-memory state: an admission that
        # exists only in memory would, after a crash, read as state=queued
        # with live pods — which recovery treats as an interrupted eviction
        # and deletes. If the annotation cannot be written the gang simply
        # stays queued and the next pump retries.
        gang.admitted_at = now
        gang.placements = placements
        if not self._persist_admitted(gang):
            gang.admitted_at = None
            gang.placements = []
            return False
        self.queue.remove(gang.key)
        gang.state = STATE_ADMITTED
        self.placer.commit(placements)
        self.ledger.charge(gang)
        self._admitted[gang.key] = gang
        SCHED_ADMISSIONS_TOTAL.inc()
        SCHED_ADMISSION_SECONDS.observe(max(0.0, now - gang.enqueued_at))
        self._event(
            gang, EVENT_GANG_ADMITTED,
            f"gang admitted after {max(0.0, now - gang.enqueued_at):.1f}s "
            f"({gang.total_chips} chip(s) reserved)",
            warning=False,
        )
        if self._wakeup is not None:
            self._wakeup(gang.key)
        return True

    def _try_preempt_for(self, gang: Gang, now: float) -> bool:
        if any(
            g.evict_deadline is not None for g in self._admitted.values()
        ):
            # Eviction capacity is already in flight behind a checkpoint
            # barrier. Selecting MORE victims against the still-charged
            # ledger would cascade evictions the pending refund may make
            # unnecessary; wait for the barrier(s) to complete — their
            # finish pumps the queue and this gang gets served then.
            return False
        victims = select_victims(
            gang, list(self._admitted.values()), self.placer, self.ledger
        )
        if not victims:
            return False
        signaled = False
        for victim in victims:
            result = self._evict(
                victim,
                annotations={
                    ANNOTATION_PREEMPTED_AT: objects.now_iso(),
                    ANNOTATION_STATE: STATE_QUEUED,
                },
                event=EVENT_PREEMPTED,
                message=(
                    f"preempted by higher-priority gang {gang.key} "
                    f"(priority {gang.priority} > {victim.priority}); "
                    "checkpoint now"
                ),
                reason="preemption",
            )
            if result == EVICT_FAILED:
                # Eviction could not be carried out (apiserver hiccup):
                # the victim keeps its capacity, so admitting the pending
                # gang now would double-book chips. Retry next pump.
                return False
            # Counted at the eviction DECISION (signal or same-pass
            # delete) — a barrier completion never re-counts.
            SCHED_PREEMPTIONS_TOTAL.inc()
            signaled = signaled or result == EVICT_SIGNALED
        if signaled:
            # Victim(s) hold their pods until ack/deadline; the pending
            # gang admits on the pump their barrier completion runs.
            return False
        return self._try_admit(gang, now)

    def _evict(
        self,
        victim: Gang,
        *,
        annotations: dict[str, str],
        event: str,
        message: str,
        aging_credit: float = 0.0,
        reason: str = "preemption",
    ) -> str:
        """Checkpoint-signal, then evict the victim WHOLE and requeue it.
        Shared by preemption (make room for a higher-priority gang) and
        fleet-health migration (get off draining/cordoned cells); the
        callers differ only in the persisted marker annotations, the
        event, and the aging credit granted on requeue.

        With a checkpoint grace configured (and a CheckpointRegistry
        attached), eviction is TWO-phase: this call persists the queued
        state + signal generation + grace deadline, stamps the signal on
        every pod, and returns EVICT_SIGNALED with the pods still running —
        the deletion loop runs later, in _finish_evict_locked, once every
        pod acked the generation or the deadline passed. Without grace it
        is the original one-pass pipeline (EVICT_DONE).

        Returns EVICT_FAILED (victim untouched, still admitted) when its
        pods cannot even be listed or the persist fails — capacity is only
        ever refunded after the deletion loop actually ran, so the
        preemptor can never be admitted onto chips the victim still
        occupies.
        """
        assert self.client is not None
        if victim.evict_deadline is not None:
            # Idempotent re-entry while the barrier is pending (repeated
            # pumps, cordon sweeps, the victim's own syncs): try to
            # complete, never re-signal — the persisted generation is the
            # one the pods are flushing against.
            return self._finish_evict_locked(victim)
        # 1. Enumerate the gang BEFORE any state changes: an unreachable
        #    apiserver aborts the eviction cleanly. Served by the informer
        #    cache when it can be authoritative (see _list_gang_pods); a
        #    cache miss of an in-flight pod is covered by the existing
        #    queued-gang-with-pods cleanup, which finishes any leftover.
        try:
            pods = self._list_gang_pods(victim)
        except ApiError:
            self.log.warning(
                "evict %s aborted: pod list failed; victim keeps capacity",
                victim.key,
            )
            return EVICT_FAILED
        barrier = (
            self.config.checkpoint_grace > 0
            and self.ckpt is not None
            and bool(pods)
        )
        now = time.time()
        ann: dict[str, Any] = dict(annotations)
        if barrier:
            gen = ckpt_protocol.new_signal_gen(now)
            deadline = now + self.config.checkpoint_grace
            ann[ckpt_protocol.JOB_SIGNAL_GEN] = str(gen)
            ann[ckpt_protocol.JOB_EVICT_DEADLINE] = (
                ckpt_protocol.fmt_deadline(deadline)
            )
        else:
            # Fire-and-forget: clear any stale barrier record an EARLIER
            # graceful eviction left behind (merge-patch null = delete),
            # so a crash between this persist and the deletion loop can
            # never read as a recovered — already expired — barrier and
            # stamp a spurious CheckpointSkipped on the way out.
            ann.setdefault(ckpt_protocol.JOB_SIGNAL_GEN, None)
            ann.setdefault(ckpt_protocol.JOB_EVICT_DEADLINE, None)
        # 2. Checkpoint signal: the annotation lands before any pod dies,
        #    giving checkpoint-aware workloads (train/checkpoint.py watches
        #    for exactly this) their flush window. Should the controller
        #    crash after this persist but before the deletion loop
        #    finishes, the successor sees state=queued with pods still
        #    present and finishes the eviction — honoring the SAME barrier,
        #    recovered from the persisted generation + deadline
        #    (reconcile_job's queued-with-pods cleanup) — never a
        #    half-evicted gang running unaccounted. If the persist itself
        #    fails the eviction aborts: deleting pods while the job still
        #    reads admitted on the wire would make a restart recover the
        #    victim as a healthy admitted gang and double-book the chips
        #    against the preemptor's.
        if not self._persist(victim.namespace, victim.name, ann):
            return EVICT_FAILED
        self._event(victim, event, message, warning=True)
        if barrier:
            # 3a. Stamp the signal on every pod — the local executor (or a
            #     sidecar on a real cluster) relays it to the workload —
            #     and HOLD the deletion loop. The gang stays admitted in
            #     memory: capacity is only refunded once pods actually
            #     die, so nothing else can be placed onto chips the victim
            #     still occupies. A pod the signal patch cannot reach is
            #     bounded by the grace deadline.
            for pod in pods:
                try:
                    self.client.patch_merge(
                        objects.PODS,
                        victim.namespace,
                        objects.name_of(pod),
                        {"metadata": {"annotations": {
                            ckpt_protocol.POD_SIGNAL: str(gen)
                        }}},
                    )
                except ApiError:
                    continue
            victim.evict_gen = gen
            victim.evict_deadline = deadline
            victim.evict_signaled_at = now
            victim.evict_credit = aging_credit
            CKPT_SIGNALS_TOTAL.inc(reason=reason)
            if self._wakeup is not None:
                self._wakeup(victim.key)
            return EVICT_SIGNALED
        # 3b. Evict the whole gang — a partial eviction would leave exactly
        #     the stranded half-slice this subsystem exists to prevent.
        for pod in pods:
            try:
                self.client.delete(
                    objects.PODS, victim.namespace, objects.name_of(pod)
                )
            except NotFound:
                continue
        self._requeue_evicted(victim, aging_credit)
        return EVICT_DONE

    def _finish_evict_locked(self, victim: Gang) -> str:
        """Complete a pending graceful eviction: once every pod acked the
        signal generation — or the grace deadline passed — run the held
        deletion loop, refund capacity, and requeue the gang. Returns
        EVICT_PENDING while the barrier still holds."""
        now = time.time()
        gen = victim.evict_gen or 0
        acked = self.ckpt is not None and self.ckpt.barrier_acked(
            victim.key, gen, victim.pod_count
        )
        if (
            not acked
            and victim.evict_deadline is not None
            and now < victim.evict_deadline
        ):
            return EVICT_PENDING
        try:
            pods = self._list_gang_pods(victim)
        except ApiError:
            return EVICT_PENDING  # retried by the next sync / health poll
        waited = now - (victim.evict_signaled_at or now)
        if acked:
            CKPT_BARRIER_SECONDS.observe(waited, result="acked")
            self._event(
                victim, EVENT_CKPT_ACKED,
                f"all {victim.pod_count} pod(s) acked the checkpoint "
                f"signal after {waited:.1f}s; evicting", warning=False,
            )
        else:
            # Grace expired with no (complete) ack: evict anyway and mark
            # the job CheckpointSkipped — losing bounded work beats
            # holding preemption/migration hostage to a mute workload.
            CKPT_BARRIER_SECONDS.observe(waited, result="expired")
            if self.ckpt is not None:
                self.ckpt.note_skipped(victim.namespace, victim.name, gen)
            self._event(
                victim, EVENT_CKPT_SKIPPED,
                f"checkpoint grace ({waited:.1f}s) expired with no ack; "
                "evicting anyway", warning=True,
            )
        for pod in pods:
            try:
                self.client.delete(
                    objects.PODS, victim.namespace, objects.name_of(pod)
                )
            except NotFound:
                continue
        self._requeue_evicted(victim, victim.evict_credit)
        # Retire the barrier record (merge-patch null deletes). Best-
        # effort: a failure leaves stale keys, which are only ever
        # consulted together with state=queued AND live pods — a
        # combination this completed deletion loop just removed.
        self._persist(victim.namespace, victim.name, {
            ckpt_protocol.JOB_SIGNAL_GEN: None,
            ckpt_protocol.JOB_EVICT_DEADLINE: None,
        })
        return EVICT_DONE

    def _requeue_evicted(self, victim: Gang, aging_credit: float) -> None:
        """Refund and requeue as a gang, keeping the original enqueue
        time (aging credit) so the victim re-admits ahead of later
        arrivals of its own class; migrations add an extra credit on
        top (the eviction was the cluster's fault)."""
        self.placer.release(victim.placements)
        self.ledger.refund(victim)
        victim.placements = []
        victim.state = STATE_QUEUED
        victim.admitted_at = None
        victim.requeues += 1
        victim.evict_gen = None
        victim.evict_deadline = None
        victim.evict_signaled_at = None
        victim.evict_credit = 0.0
        if aging_credit:
            victim.enqueued_at -= aging_credit
        self._admitted.pop(victim.key, None)
        self.queue.add(victim)
        if self._wakeup is not None:
            self._wakeup(victim.key)

    def _forget(self, gang: Gang) -> None:
        if gang.state == STATE_ADMITTED:
            self.placer.release(gang.placements)
            self.ledger.refund(gang)
        self._admitted.pop(gang.key, None)
        self.queue.remove(gang.key)

    # -- persistence / events -------------------------------------------------

    def _persist_admitted(self, gang: Gang) -> bool:
        return self._persist(
            gang.namespace, gang.name,
            {
                ANNOTATION_STATE: STATE_ADMITTED,
                ANNOTATION_ADMITTED_AT: _fmt_epoch(gang.admitted_at or time.time()),
                ANNOTATION_ENQUEUED_AT: _fmt_epoch(gang.enqueued_at),
                ANNOTATION_CHIPS: str(gang.total_chips),
                ANNOTATION_PLACEMENTS: json.dumps(
                    [p.to_dict() for p in gang.placements]
                ),
            },
        )

    def _persist(
        self,
        namespace: str,
        name: str,
        annotations: dict[str, Any],
        typed: TPUJob | None = None,
    ) -> bool:
        """Merge-patch annotations onto the job (a None value deletes the
        key, RFC 7386). Returns False on failure (a vanished job, an
        apiserver error) so callers for whom the persisted state is a
        prerequisite — admission, eviction — can abort instead of
        diverging from what a restart would recover. When the caller holds
        the typed object, its RV is refreshed so the sync's later status
        write does not self-conflict."""
        if self.client is None:
            return True
        try:
            patched = self.client.patch_merge(
                objects.TPUJOBS, namespace, name,
                {"metadata": {"annotations": dict(annotations)}},
            )
        except ApiError:
            self.log.warning(
                "annotation persist failed for %s/%s", namespace, name
            )
            return False
        if typed is not None:
            for k, v in annotations.items():
                if v is None:
                    typed.metadata.annotations.pop(k, None)
                else:
                    typed.metadata.annotations[k] = v
            typed.metadata.resource_version = str(
                objects.meta(patched).get("resourceVersion", "")
            )
        return True

    def _event(self, gang: Gang, reason: str, message: str, warning: bool) -> None:
        if self.recorder is None:
            return
        ref = {
            "apiVersion": constants.API_VERSION,
            "kind": constants.KIND,
            "metadata": {
                "namespace": gang.namespace,
                "name": gang.name,
                "uid": gang.uid,
            },
        }
        try:
            if warning:
                self.recorder.warning(ref, reason, message)
            else:
                self.recorder.normal(ref, reason, message)
        except Exception:  # events are best-effort observability
            self.log.debug("event emit failed", exc_info=True)

    def _export_gauges(self) -> None:
        SCHED_QUEUE_DEPTH.set(len(self.queue))
        SCHED_ADMITTED_GANGS.set(len(self._admitted))
        for gen, used in self.placer.chips_in_use().items():
            SCHED_CHIPS_IN_USE.set(used, generation=gen)


def _fmt_epoch(epoch: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(epoch))


def _parse_epoch(stamp: str | None) -> float | None:
    if not stamp:
        return None
    try:
        import calendar

        return float(
            calendar.timegm(time.strptime(stamp, "%Y-%m-%dT%H:%M:%SZ"))
        )
    except ValueError:
        return None
