"""Slice-aware gang scheduling: all-or-nothing admission, quota, priority,
preemption. See docs/scheduler.md for the pipeline walkthrough."""

from tf_operator_tpu.scheduler.core import (
    AdmissionDecision,
    GangScheduler,
    SchedulerConfig,
)
from tf_operator_tpu.scheduler.gang import (
    GATE_NAME,
    Gang,
    gang_from_job,
    is_gated,
    resolve_priority,
)
from tf_operator_tpu.scheduler.placement import (
    Placement,
    TopologyPlacer,
    parse_capacity,
)
from tf_operator_tpu.scheduler.preemption import select_victims
from tf_operator_tpu.scheduler.queue import AdmissionQueue, Quota, QuotaLedger

__all__ = [
    "AdmissionDecision",
    "AdmissionQueue",
    "GATE_NAME",
    "Gang",
    "GangScheduler",
    "Placement",
    "Quota",
    "QuotaLedger",
    "SchedulerConfig",
    "TopologyPlacer",
    "gang_from_job",
    "is_gated",
    "parse_capacity",
    "resolve_priority",
    "select_victims",
]
