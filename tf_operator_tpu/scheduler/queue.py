"""Admission queue: priority FIFO with quota accounting and aging.

Ordering: gangs are served by *effective* priority — the static priority
resolved from ``SchedulingPolicy.priority_class`` plus an aging bonus that
grows with time spent queued. Ties break FIFO (enqueue time, then name).
Aging is the starvation valve: a low-priority gang stuck behind a stream
of high-priority arrivals eventually out-bids them in QUEUE POSITION, so
it holds first claim on the next capacity that frees up and no tenant
waits forever behind a busy stream. Aging deliberately does not grant
eviction rights — preemption stays keyed on static class (see
preemption.py; an aged gang evicting a peer would requeue that peer with
its own retained aging credit and see-saw forever).

Head-of-line discipline is strict for free capacity: the pump never lets
a later gang take free chips past a blocked head — backfill would starve
large slices indefinitely on a busy fleet, exactly the workloads gang
admission exists for. Later gangs may still be served by preemption,
which takes capacity from their own strictly-lower-class victims rather
than from the pool the head is waiting on (core.py ``_pump``).

Quota: per-namespace budgets in chips and/or slice count, charged at
admission and refunded at release/preemption/terminal — the multi-tenant
arbitration layer the ROADMAP's many-concurrent-jobs target needs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from tf_operator_tpu.scheduler.gang import Gang


@dataclass(frozen=True)
class Quota:
    """A namespace's admission budget; None = unlimited on that axis."""

    chips: int | None = None
    slices: int | None = None


class QuotaLedger:
    """Charges admitted gangs against per-namespace budgets."""

    def __init__(self, quotas: dict[str, Quota] | None = None) -> None:
        self.quotas = dict(quotas or {})
        self._chips: dict[str, int] = {}
        self._slices: dict[str, int] = {}

    def fits(self, gang: Gang) -> bool:
        quota = self.quotas.get(gang.namespace)
        if quota is None:
            return True
        if quota.chips is not None:
            if self._chips.get(gang.namespace, 0) + gang.total_chips > quota.chips:
                return False
        if quota.slices is not None:
            if (
                self._slices.get(gang.namespace, 0) + gang.num_slices
                > quota.slices
            ):
                return False
        return True

    def fits_ever(self, gang: Gang) -> bool:
        """Could this gang EVER pass quota, even on an idle namespace?
        False = permanently infeasible, however much capacity frees up."""
        quota = self.quotas.get(gang.namespace)
        if quota is None:
            return True
        if quota.chips is not None and gang.total_chips > quota.chips:
            return False
        if quota.slices is not None and gang.num_slices > quota.slices:
            return False
        return True

    def charge(self, gang: Gang) -> None:
        ns = gang.namespace
        self._chips[ns] = self._chips.get(ns, 0) + gang.total_chips
        self._slices[ns] = self._slices.get(ns, 0) + gang.num_slices

    def refund(self, gang: Gang) -> None:
        ns = gang.namespace
        self._chips[ns] = max(0, self._chips.get(ns, 0) - gang.total_chips)
        self._slices[ns] = max(0, self._slices.get(ns, 0) - gang.num_slices)

    def usage(self) -> dict[str, dict[str, int]]:
        namespaces = set(self._chips) | set(self._slices) | set(self.quotas)
        return {
            ns: {
                "chips": self._chips.get(ns, 0),
                "slices": self._slices.get(ns, 0),
            }
            for ns in sorted(namespaces)
        }


class AdmissionQueue:
    """The waiting line. Not thread-safe; GangScheduler holds the lock."""

    def __init__(self, aging_rate: float = 1.0) -> None:
        # Priority points gained per second of queue wait. At the default
        # (1 pt/s) a "default" (0) gang out-bids a "high" (100) arrival
        # after 100s of waiting — aggressive enough for tests and small
        # fleets; production deployments tune it down via SchedulerConfig.
        self.aging_rate = aging_rate
        self._gangs: dict[str, Gang] = {}

    def __len__(self) -> int:
        return len(self._gangs)

    def __contains__(self, key: str) -> bool:
        return key in self._gangs

    def get(self, key: str) -> Gang | None:
        return self._gangs.get(key)

    def add(self, gang: Gang) -> None:
        self._gangs[gang.key] = gang

    def remove(self, key: str) -> Gang | None:
        return self._gangs.pop(key, None)

    def effective_priority(self, gang: Gang, now: float | None = None) -> float:
        waited = max(0.0, (now if now is not None else time.time()) - gang.enqueued_at)
        return gang.priority + self.aging_rate * waited

    def ordered(self, now: float | None = None) -> list[Gang]:
        """Service order: effective priority desc, then FIFO, then name."""
        now = now if now is not None else time.time()
        return sorted(
            self._gangs.values(),
            key=lambda g: (
                -self.effective_priority(g, now),
                g.enqueued_at,
                g.key,
            ),
        )
