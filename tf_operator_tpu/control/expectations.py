"""ControllerExpectations: the create/observe race breaker.

Parity: the k8s.io/kubernetes ControllerExpectations the reference leans on
(documented at jobcontroller.go:90-104, wired at tfcontroller.go:143). The
controller's informer cache lags its own writes; without expectations a
second sync between "created pod" and "saw pod in cache" would create
duplicates. Before acting, a sync checks `satisfied(key)`; after issuing
creates/deletes it bumps the expected counts; informer events decrement them.
Entries expire after 5 minutes so a lost event can't wedge a job forever —
critical here because gang-creating a 4-host slice quadruples the window
(SURVEY.md §7 "create/observe races").
"""

from __future__ import annotations

import threading
import time

EXPECTATION_TIMEOUT = 5 * 60.0


class _Expectation:
    __slots__ = ("adds", "dels", "timestamp")

    def __init__(self, adds: int = 0, dels: int = 0) -> None:
        self.adds = adds
        self.dels = dels
        self.timestamp = time.monotonic()

    def fulfilled(self) -> bool:
        return self.adds <= 0 and self.dels <= 0

    def expired(self) -> bool:
        return time.monotonic() - self.timestamp > EXPECTATION_TIMEOUT


class ControllerExpectations:
    """Keys are controller-chosen strings; the TPU controller uses
    "{ns}/{name}/{replica-type}/pods" and ".../services"."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._store: dict[str, _Expectation] = {}

    def expect_creations(self, key: str, count: int) -> None:
        with self._lock:
            self._store[key] = _Expectation(adds=count)

    def expect_deletions(self, key: str, count: int) -> None:
        with self._lock:
            self._store[key] = _Expectation(dels=count)

    def raise_expectations(self, key: str, adds: int, dels: int) -> None:
        with self._lock:
            exp = self._store.get(key)
            if exp is None:
                exp = self._store[key] = _Expectation()
            exp.adds += adds
            exp.dels += dels

    def creation_observed(self, key: str) -> None:
        self._lower(key, add_delta=-1)

    def deletion_observed(self, key: str) -> None:
        self._lower(key, del_delta=-1)

    def _lower(self, key: str, add_delta: int = 0, del_delta: int = 0) -> None:
        with self._lock:
            exp = self._store.get(key)
            if exp is not None:
                exp.adds += add_delta
                exp.dels += del_delta

    def satisfied(self, key: str) -> bool:
        """True when it's safe to act on the world view for this key."""
        with self._lock:
            exp = self._store.get(key)
            if exp is None:
                return True
            if exp.fulfilled() or exp.expired():
                return True
            return False

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)
