"""ControllerRef claim/adopt/orphan manager.

Parity: pkg/control/service_ref_manager.go:32-160 and the upstream
PodControllerRefManager the reference uses via GetPodsForJob
(jobcontroller.go:145-193). Reconciles list results against ownership:

- matches selector + no controller → ADOPT (patch in our ownerReference),
  unless the job is being deleted (CanAdopt recheck);
- owned by us + no longer matches selector → ORPHAN (patch the ref out);
- owned by someone else → ignore.

Claiming makes the controller self-healing against manual label edits and
lets it pick up pre-existing resources after an operator restart.
"""

from __future__ import annotations

from typing import Any, Callable

from tf_operator_tpu.api.helpers import selector_matches
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ApiError, ClusterClient, NotFound


class RefManager:
    def __init__(
        self,
        client: ClusterClient,
        controller_obj: dict[str, Any],
        controller_ref: dict[str, Any],
        selector: dict[str, str],
        can_adopt: Callable[[], bool] | None = None,
    ) -> None:
        self._client = client
        self._obj = controller_obj
        self._ref = controller_ref
        self._selector = selector
        self._can_adopt = can_adopt or (
            lambda: not objects.is_deleted(controller_obj)
        )

    def _claim_one(self, kind: str, obj: dict[str, Any]) -> dict[str, Any] | None:
        controller = None
        for ref in objects.meta(obj).get("ownerReferences", []):
            if ref.get("controller"):
                controller = ref
                break
        matches = selector_matches(self._selector, objects.labels_of(obj))

        if controller is not None:
            if controller.get("uid") != self._ref.get("uid"):
                return None  # owned by someone else
            if matches:
                return obj
            # Ours but no longer matching: orphan it.
            self._orphan(kind, obj)
            return None

        if not matches or objects.is_deleted(obj):
            return None
        if not self._can_adopt():
            return None
        return self._adopt(kind, obj)

    def _adopt(self, kind: str, obj: dict[str, Any]) -> dict[str, Any] | None:
        refs = list(objects.meta(obj).get("ownerReferences", []))
        refs.append(dict(self._ref))
        try:
            return self._client.patch_merge(
                kind,
                objects.namespace_of(obj),
                objects.name_of(obj),
                {"metadata": {"ownerReferences": refs}},
            )
        except NotFound:
            return None
        except ApiError:
            return None

    def _orphan(self, kind: str, obj: dict[str, Any]) -> None:
        refs = [
            r
            for r in objects.meta(obj).get("ownerReferences", [])
            if r.get("uid") != self._ref.get("uid")
        ]
        try:
            self._client.patch_merge(
                kind,
                objects.namespace_of(obj),
                objects.name_of(obj),
                {"metadata": {"ownerReferences": refs}},
            )
        except ApiError:
            pass

    def claim(self, kind: str, candidates: list[dict[str, Any]]) -> list[dict[str, Any]]:
        claimed = []
        for obj in candidates:
            got = self._claim_one(kind, obj)
            if got is not None:
                claimed.append(got)
        return claimed

    def claim_pods(self, pods: list[dict[str, Any]]) -> list[dict[str, Any]]:
        return self.claim(objects.PODS, pods)

    def claim_services(self, services: list[dict[str, Any]]) -> list[dict[str, Any]]:
        return self.claim(objects.SERVICES, services)
