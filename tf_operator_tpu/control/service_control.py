"""Service create/delete control, mirroring pod_control.

Parity: pkg/control/service_control.go:41-207 (RealServiceControl +
FakeServiceControl with CreateLimit).
"""

from __future__ import annotations

import copy
from typing import Any

from tf_operator_tpu.runtime import events as ev
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ApiError, ClusterClient
from tf_operator_tpu.control.pod_control import validate_controller_ref


class ServiceControlInterface:
    def create_service(
        self,
        namespace: str,
        service: dict[str, Any],
        controller_object: dict[str, Any],
        controller_ref: dict[str, Any],
    ) -> dict[str, Any]:
        raise NotImplementedError

    def delete_service(
        self, namespace: str, name: str, controller_object: dict[str, Any]
    ) -> None:
        raise NotImplementedError

    def patch_service(
        self, namespace: str, name: str, patch: dict[str, Any]
    ) -> dict[str, Any]:
        raise NotImplementedError


class RealServiceControl(ServiceControlInterface):
    def __init__(self, client: ClusterClient, recorder: ev.EventRecorder) -> None:
        self._client = client
        self._recorder = recorder

    def create_service(self, namespace, service, controller_object, controller_ref):
        validate_controller_ref(controller_ref)
        service = copy.deepcopy(service)
        meta = objects.meta(service)
        meta["namespace"] = namespace
        refs = meta.setdefault("ownerReferences", [])
        if not any(r.get("uid") == controller_ref["uid"] for r in refs):
            refs.append(copy.deepcopy(controller_ref))
        try:
            created = self._client.create(objects.SERVICES, service)
        except ApiError as e:
            self._recorder.warning(
                controller_object, ev.FAILED_CREATE_SERVICE, f"Error creating: {e}"
            )
            raise
        self._recorder.normal(
            controller_object,
            ev.SUCCESSFUL_CREATE_SERVICE,
            f"Created service: {objects.name_of(created)}",
        )
        return created

    def delete_service(self, namespace, name, controller_object):
        try:
            self._client.delete(objects.SERVICES, namespace, name)
        except ApiError as e:
            self._recorder.warning(
                controller_object,
                ev.FAILED_DELETE_SERVICE,
                f"Error deleting {name}: {e}",
            )
            raise
        self._recorder.normal(
            controller_object, ev.SUCCESSFUL_DELETE_SERVICE, f"Deleted service: {name}"
        )

    def patch_service(self, namespace, name, patch):
        return self._client.patch_merge(objects.SERVICES, namespace, name, patch)


class FakeServiceControl(ServiceControlInterface):
    """Parity: service_control.go:136-207."""

    def __init__(self) -> None:
        self.templates: list[dict[str, Any]] = []
        self.delete_service_names: list[str] = []
        self.patches: list[dict[str, Any]] = []
        self.create_limit = 0
        self.create_error: Exception | None = None

    def create_service(self, namespace, service, controller_object, controller_ref):
        validate_controller_ref(controller_ref)
        if self.create_limit and len(self.templates) >= self.create_limit:
            raise ApiError("fake create limit exceeded")
        if self.create_error is not None:
            raise self.create_error
        self.templates.append(copy.deepcopy(service))
        return service

    def delete_service(self, namespace, name, controller_object):
        self.delete_service_names.append(name)

    def patch_service(self, namespace, name, patch):
        self.patches.append(copy.deepcopy(patch))
        return patch

    def clear(self) -> None:
        self.templates.clear()
        self.delete_service_names.clear()
        self.patches.clear()
