"""Pod create/delete with owner-ref stamping + event emission.

Parity: pkg/control/pod_control.go (RealPodControl, forked from k8s core to
control naming) and upstream controller.FakePodControl used by the tier-2
tests. Creation validates the controller ownerReference, stamps labels, and
records Normal/Warning events; deletion refuses pods already terminating.
"""

from __future__ import annotations

import copy
from typing import Any

from tf_operator_tpu.runtime import events as ev
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ApiError, ClusterClient


class PodControlInterface:
    def create_pod(
        self,
        namespace: str,
        pod: dict[str, Any],
        controller_object: dict[str, Any],
        controller_ref: dict[str, Any],
    ) -> dict[str, Any]:
        raise NotImplementedError

    def delete_pod(
        self, namespace: str, name: str, controller_object: dict[str, Any]
    ) -> None:
        raise NotImplementedError


def validate_controller_ref(ref: dict[str, Any]) -> None:
    if not ref.get("uid"):
        raise ValueError("controllerRef has no UID")
    if not ref.get("apiVersion") or not ref.get("kind"):
        raise ValueError("controllerRef needs apiVersion and kind")
    if not ref.get("controller"):
        raise ValueError("controllerRef must have controller=true")


class RealPodControl(PodControlInterface):
    def __init__(self, client: ClusterClient, recorder: ev.EventRecorder) -> None:
        self._client = client
        self._recorder = recorder

    def create_pod(self, namespace, pod, controller_object, controller_ref):
        validate_controller_ref(controller_ref)
        pod = copy.deepcopy(pod)
        meta = objects.meta(pod)
        meta["namespace"] = namespace
        refs = meta.setdefault("ownerReferences", [])
        if not any(r.get("uid") == controller_ref["uid"] for r in refs):
            refs.append(copy.deepcopy(controller_ref))
        try:
            created = self._client.create(objects.PODS, pod)
        except ApiError as e:
            self._recorder.warning(
                controller_object, ev.FAILED_CREATE_POD, f"Error creating: {e}"
            )
            raise
        self._recorder.normal(
            controller_object,
            ev.SUCCESSFUL_CREATE_POD,
            f"Created pod: {objects.name_of(created)}",
        )
        return created

    def delete_pod(self, namespace, name, controller_object):
        try:
            pod = self._client.get(objects.PODS, namespace, name)
            if objects.is_deleted(pod):
                raise ApiError(f"pod {namespace}/{name} is already terminating")
            self._client.delete(objects.PODS, namespace, name)
        except ApiError as e:
            self._recorder.warning(
                controller_object, ev.FAILED_DELETE_POD, f"Error deleting {name}: {e}"
            )
            raise
        self._recorder.normal(
            controller_object, ev.SUCCESSFUL_DELETE_POD, f"Deleted pod: {name}"
        )


class FakePodControl(PodControlInterface):
    """Records intents for assertions; optional create limit + injected errors."""

    def __init__(self) -> None:
        self.templates: list[dict[str, Any]] = []
        self.controller_refs: list[dict[str, Any]] = []
        self.delete_pod_names: list[str] = []
        self.create_limit = 0  # 0 = unlimited
        self.create_error: Exception | None = None
        self.delete_error: Exception | None = None

    def create_pod(self, namespace, pod, controller_object, controller_ref):
        validate_controller_ref(controller_ref)
        if self.create_limit and len(self.templates) >= self.create_limit:
            raise ApiError("fake create limit exceeded")
        if self.create_error is not None:
            raise self.create_error
        self.templates.append(copy.deepcopy(pod))
        self.controller_refs.append(copy.deepcopy(controller_ref))
        return pod

    def delete_pod(self, namespace, name, controller_object):
        if self.delete_error is not None:
            raise self.delete_error
        self.delete_pod_names.append(name)

    def clear(self) -> None:
        self.templates.clear()
        self.controller_refs.clear()
        self.delete_pod_names.clear()
