"""Ring attention: exact attention over sequences sharded across devices.

Long-context support is first-class in this framework (SURVEY.md notes the
reference predates sequence parallelism entirely): the sequence axis is
sharded over the mesh's ``sp`` axis, each device holds one Q/K/V block, and
K/V blocks rotate around the ring via ``lax.ppermute`` over ICI while a
streaming (flash-style) softmax accumulates exact results — O(T/sp) memory
per device, communication overlapped with the next block's compute by XLA.

Shapes follow [batch, seq, heads, head_dim]. Works under shard_map on any
mesh axis; used by models/transformer.py when ``sp > 1``. Two
implementations share the contract:

- ``ring_attention`` — streaming softmax, differentiable by autodiff
  through the scan+ppermute (tapes every ring step); supports
  ``kv_chunk`` to bound the per-step score tile.
- ``ring_flash_attention`` — custom VJP: the backward runs a second ring
  (no forward tape), and per-block compute uses the Pallas flash kernels
  on TPU. The training default on TPU (TransformerConfig.ring_impl).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from tf_operator_tpu import parallel as parallel_compat

_NEG_INF = -1e30  # finite "masked" value: keeps the streaming max NaN-free


def _block_attn(q, k, v, scale, mask):
    """One q-block x kv-block attention contribution.

    Returns (scores_max, exp_scores, pv): pieces for streaming softmax.
    q: [B,Tq,H,D]  k,v: [B,Tk,H,D]  mask: [Tq,Tk] bool (True = keep) or None.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, _NEG_INF)
    return s


def _accumulate_block(q, k_blk, v_blk, q_pos, k_pos, o, m, l, scale, causal):
    """Fold one kv block into the streaming-softmax accumulators."""
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
    else:
        mask = None
    s = _block_attn(q, k_blk, v_blk, scale, mask)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # exp(_NEG_INF - _NEG_INF) would be 1; clamp fully-masked rows via l.
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    if causal:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    l = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
    o = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return o, m_new, l


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, scale: float,
                          kv_chunk: int | None = None):
    """Per-device body (runs under shard_map). Local seq block attends to
    every kv block as it rotates around the ring.

    kv_chunk bounds the score tile: each held kv block is folded in chunks
    of that many keys through an inner scan, so per-device live memory is
    O(Tq * kv_chunk) instead of O(Tq * Tk) — the long-context regime where
    even one device's block pair would not fit. Exact either way (the
    streaming softmax is associative over chunks).
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if kv_chunk is not None and (kv_chunk <= 0 or tk % kv_chunk):
        raise ValueError(f"kv_chunk {kv_chunk} must divide the kv block {tk}")

    o = jnp.zeros((b, tq, h, d), jnp.float32)
    m = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tq), jnp.float32)

    q_pos = my_idx * tq + jnp.arange(tq)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        # Which global block this device currently holds: blocks rotate
        # forward, so at step i we hold block (my_idx - i) mod ring.
        kv_idx = (my_idx - i) % axis_size
        k0 = kv_idx * tk
        if kv_chunk is None:
            o, m, l = _accumulate_block(
                q, k_cur, v_cur, q_pos, k0 + jnp.arange(tk), o, m, l,
                scale, causal,
            )
        else:
            def chunk_step(inner, j):
                o, m, l = inner
                k_blk = lax.dynamic_slice_in_dim(k_cur, j * kv_chunk, kv_chunk, 1)
                v_blk = lax.dynamic_slice_in_dim(v_cur, j * kv_chunk, kv_chunk, 1)
                k_pos = k0 + j * kv_chunk + jnp.arange(kv_chunk)
                return _accumulate_block(
                    q, k_blk, v_blk, q_pos, k_pos, o, m, l, scale, causal
                ), None

            (o, m, l), _ = lax.scan(
                chunk_step, (o, m, l), jnp.arange(tk // kv_chunk)
            )
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_next, v_next), None

    (o, m, l, _, _), _ = lax.scan(
        step, (o, m, l, k, v), jnp.arange(axis_size)
    )
    l = jnp.maximum(l, 1e-30)  # fully-masked rows (shouldn't occur causally)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "sp",
    batch_spec: Any = ("dp",),
    head_spec: Any = (None,),
    causal: bool = True,
    scale: float | None = None,
    kv_chunk: int | None = None,
) -> jax.Array:
    """Exact attention with the sequence dim sharded over ``seq_axis``.

    q/k/v: [batch, seq, heads, head_dim] global arrays (sharded or to-be-
    sharded per the specs). Returns the attention output with the same
    sharding as q. ``kv_chunk`` (must divide the per-device block) bounds
    per-device score memory to O(Tq * kv_chunk) for long-context blocks.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(*batch_spec, seq_axis, *head_spec, None)
    body = functools.partial(
        _ring_attention_local, axis_name=seq_axis, causal=causal, scale=scale,
        kv_chunk=kv_chunk,
    )
    return parallel_compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Custom-VJP ring attention ("ring flash"): the long-context training path.
#
# The streaming implementation above differentiates by taping every ring
# step (autodiff through scan): O(ring) saved score tiles. This variant
# instead saves only (q, k, v, out, lse) per device and runs a SECOND ring
# in the backward — the standard ring-attention gradient — with rotating
# dk/dv accumulators that travel with their k/v blocks and arrive home
# after a full rotation. Exactness hinges on one identity: with the
# GLOBAL logsumexp, each block's softmax share is p = exp(s_blk - lse),
# so per-block forward results merge by logaddexp and per-block backward
# needs no inter-block communication beyond the rotation itself.
#
# Per-block compute dispatches to the Pallas flash kernels on TPU
# (ops/flash_attention.py — fwd returns (o, lse); dq/dkv recompute from
# the global lse), with an XLA fallback elsewhere; under causal masking a
# ring step is one of exactly three modes: the diagonal block (aligned
# causal), a past block (full attention), or a future block (skipped —
# no FLOPs, no softmax statistics).
# ---------------------------------------------------------------------------


def _xla_block_fwd(q, k, v, scale, causal):
    """(o_f32, lse) for one q-block x kv-block pair, XLA path.

    lse: [B, H, Tq] global-softmax statistics for THIS block alone.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = s.max(axis=-1)
    p = jnp.exp(s - m[..., None])
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.maximum(p.sum(axis=-1), 1e-30)
    o = jnp.einsum("bhqk,bkhd->bqhd", p / l[..., None],
                   v.astype(jnp.float32))
    return o, m + jnp.log(l)


def _xla_block_bwd(q, k, v, do, lse, delta, scale, causal):
    """(dq, dk, dv) for one block pair given GLOBAL lse and delta."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    p = jnp.exp(s - lse[..., None])
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        p = jnp.where(mask[None, None], p, 0.0)
    do32 = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", do32, v.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32))
    return dq, dk, dv


def _use_flash_blocks(tq: int, tk: int) -> bool:
    from tf_operator_tpu.ops.flash_attention import (
        on_tpu_backend,
        select_block,
    )

    return on_tpu_backend() and select_block(tq, tk, compiled=True) is not None


def _kernel_block_fwd(q, k, v, scale, causal):
    """Pallas flash fwd for one block pair: (o_f32, lse [B,H,Tq])."""
    from tf_operator_tpu.ops.flash_attention import (
        _flash_fwd,
        on_tpu_backend,
        select_block_pair,
    )

    interpret = not on_tpu_backend()  # CPU tests drive the kernel path
    bq, bk = select_block_pair(q.shape[1], k.shape[1],
                               compiled=not interpret)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o, lse = _flash_fwd(qt, kt, vt, causal, scale, bq, bk, interpret)
    return o.transpose(0, 2, 1, 3).astype(jnp.float32), lse[..., 0]


def _kernel_block_bwd(q, k, v, do, lse, delta, scale, causal):
    """Pallas flash bwd for one block pair from GLOBAL lse/delta (the
    shared stats-accepting core in ops/flash_attention.py)."""
    from tf_operator_tpu.ops.flash_attention import (
        _flash_bwd_from_stats,
        on_tpu_backend,
        select_block_pair,
    )

    interpret = not on_tpu_backend()
    bq, bk = select_block_pair(q.shape[1], k.shape[1],
                               compiled=not interpret)
    qt, kt, vt, dot = (x.transpose(0, 2, 1, 3) for x in (q, k, v, do))
    dq, dk, dv = _flash_bwd_from_stats(
        qt, kt, vt, dot, lse[..., None], delta[..., None],
        causal, scale, bq, bk, interpret,
    )
    return (
        dq.transpose(0, 2, 1, 3).astype(jnp.float32),
        dk.transpose(0, 2, 1, 3).astype(jnp.float32),
        dv.transpose(0, 2, 1, 3).astype(jnp.float32),
    )


def _merge_block(o, lse, o_blk, lse_blk):
    """Fold one block's (o, lse) into the global accumulators."""
    lse_new = jnp.logaddexp(lse, lse_blk)
    w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
    w_blk = jnp.exp(lse_blk - lse_new).transpose(0, 2, 1)[..., None]
    return o * w_old + o_blk * w_blk, lse_new


def _make_ring_flash_local(axis_name: str, causal: bool, scale: float,
                           use_kernel: bool):
    """Build the per-device custom-VJP body (runs under shard_map)."""
    block_fwd = _kernel_block_fwd if use_kernel else _xla_block_fwd
    block_bwd = _kernel_block_bwd if use_kernel else _xla_block_bwd

    @jax.custom_vjp
    def local(q, k, v):
        out, _ = _fwd(q, k, v)
        return out

    def _fwd(q, k, v):
        axis_size = lax.psum(1, axis_name)
        my_idx = lax.axis_index(axis_name)
        b, tq, h, d = q.shape
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        o0 = jnp.zeros((b, tq, h, d), jnp.float32)
        lse0 = jnp.full((b, h, tq), _NEG_INF, jnp.float32)

        def step(carry, i):
            o, lse, k_cur, v_cur = carry
            kv_idx = (my_idx - i) % axis_size
            if causal:
                def diag(_):
                    return block_fwd(q, k_cur, v_cur, scale, True)

                def past(_):
                    return block_fwd(q, k_cur, v_cur, scale, False)

                def future(_):
                    return (jnp.zeros_like(o0),
                            jnp.full_like(lse0, _NEG_INF))

                mode = jnp.where(
                    kv_idx == my_idx, 0, jnp.where(kv_idx < my_idx, 1, 2)
                )
                o_blk, lse_blk = lax.switch(mode, (diag, past, future), None)
            else:
                o_blk, lse_blk = block_fwd(q, k_cur, v_cur, scale, False)
            o, lse = _merge_block(o, lse, o_blk, lse_blk)
            return (
                o, lse,
                lax.ppermute(k_cur, axis_name, perm),
                lax.ppermute(v_cur, axis_name, perm),
            ), None

        (o, lse, _, _), _ = lax.scan(
            step, (o0, lse0, k, v), jnp.arange(axis_size)
        )
        return o.astype(q.dtype), lse

    def fwd(q, k, v):
        out, lse = _fwd(q, k, v)
        return out, (q, k, v, out, lse)

    def bwd(res, do):
        q, k, v, out, lse = res
        axis_size = lax.psum(1, axis_name)
        my_idx = lax.axis_index(axis_name)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        delta = jnp.einsum(
            "bqhd,bqhd->bhq", do.astype(jnp.float32),
            out.astype(jnp.float32),
        )

        zeros_kv = jnp.zeros(k.shape, jnp.float32)

        def step(carry, i):
            dq, k_cur, v_cur, dk_cur, dv_cur = carry
            kv_idx = (my_idx - i) % axis_size
            if causal:
                def diag(_):
                    return block_bwd(q, k_cur, v_cur, do, lse, delta,
                                     scale, True)

                def past(_):
                    return block_bwd(q, k_cur, v_cur, do, lse, delta,
                                     scale, False)

                def future(_):
                    return jnp.zeros_like(dq), zeros_kv, zeros_kv

                mode = jnp.where(
                    kv_idx == my_idx, 0, jnp.where(kv_idx < my_idx, 1, 2)
                )
                dq_b, dk_b, dv_b = lax.switch(mode, (diag, past, future), None)
            else:
                dq_b, dk_b, dv_b = block_bwd(q, k_cur, v_cur, do, lse,
                                             delta, scale, False)
            dq = dq + dq_b
            # The dk/dv accumulators travel WITH their k/v blocks: after a
            # full rotation they arrive back at the block's home device.
            return (
                dq,
                lax.ppermute(k_cur, axis_name, perm),
                lax.ppermute(v_cur, axis_name, perm),
                lax.ppermute(dk_cur + dk_b, axis_name, perm),
                lax.ppermute(dv_cur + dv_b, axis_name, perm),
            ), None

        dq0 = jnp.zeros(q.shape, jnp.float32)
        (dq, _, _, dk, dv), _ = lax.scan(
            step, (dq0, k, v, zeros_kv, zeros_kv), jnp.arange(axis_size)
        )
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    local.defvjp(fwd, bwd)
    return local


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "sp",
    batch_spec: Any = ("dp",),
    head_spec: Any = (None,),
    causal: bool = True,
    scale: float | None = None,
    use_kernel: bool | None = None,
) -> jax.Array:
    """Custom-VJP ring attention (see module section comment).

    Same contract as ring_attention; the backward runs a second ring
    instead of taping the forward scan (O(1) saved tensors per device vs
    O(ring steps)), and per-block compute uses the Pallas flash kernels
    when on TPU with tileable per-device blocks (``use_kernel`` forces
    the choice for tests).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if causal and q.shape[1] != k.shape[1]:
        # The diag/past/future block classification and the per-block masks
        # assume aligned equal blocks; ring_attention's global-position
        # masking handles the rectangular causal case.
        raise ValueError(
            f"causal ring_flash_attention requires equal q/kv seq lengths "
            f"(got {q.shape[1]}, {k.shape[1]}); use ring_attention"
        )
    sp = mesh.shape.get(seq_axis, 1)
    tq = q.shape[1] // sp
    tk = k.shape[1] // sp
    if use_kernel is None:
        use_kernel = _use_flash_blocks(tq, tk)
    spec = P(*batch_spec, seq_axis, *head_spec, None)
    body = _make_ring_flash_local(seq_axis, causal, float(scale),
                                  bool(use_kernel))
    return parallel_compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def reference_attention(q, k, v, causal: bool = True, scale: float | None = None):
    """Single-device exact attention — the correctness oracle for tests."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
