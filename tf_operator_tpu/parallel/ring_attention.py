"""Ring attention: exact attention over sequences sharded across devices.

Long-context support is first-class in this framework (SURVEY.md notes the
reference predates sequence parallelism entirely): the sequence axis is
sharded over the mesh's ``sp`` axis, each device holds one Q/K/V block, and
K/V blocks rotate around the ring via ``lax.ppermute`` over ICI while a
streaming (flash-style) softmax accumulates exact results — O(T/sp) memory
per device, communication overlapped with the next block's compute by XLA.

Shapes follow [batch, seq, heads, head_dim]. Works under shard_map on any
mesh axis; differentiable (autodiff through the scan+ppermute); used by
models/transformer.py when ``sp > 1``.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30  # finite "masked" value: keeps the streaming max NaN-free


def _block_attn(q, k, v, scale, mask):
    """One q-block x kv-block attention contribution.

    Returns (scores_max, exp_scores, pv): pieces for streaming softmax.
    q: [B,Tq,H,D]  k,v: [B,Tk,H,D]  mask: [Tq,Tk] bool (True = keep) or None.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, _NEG_INF)
    return s


def _accumulate_block(q, k_blk, v_blk, q_pos, k_pos, o, m, l, scale, causal):
    """Fold one kv block into the streaming-softmax accumulators."""
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
    else:
        mask = None
    s = _block_attn(q, k_blk, v_blk, scale, mask)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # exp(_NEG_INF - _NEG_INF) would be 1; clamp fully-masked rows via l.
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    if causal:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    l = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
    o = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return o, m_new, l


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, scale: float,
                          kv_chunk: int | None = None):
    """Per-device body (runs under shard_map). Local seq block attends to
    every kv block as it rotates around the ring.

    kv_chunk bounds the score tile: each held kv block is folded in chunks
    of that many keys through an inner scan, so per-device live memory is
    O(Tq * kv_chunk) instead of O(Tq * Tk) — the long-context regime where
    even one device's block pair would not fit. Exact either way (the
    streaming softmax is associative over chunks).
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if kv_chunk is not None and (kv_chunk <= 0 or tk % kv_chunk):
        raise ValueError(f"kv_chunk {kv_chunk} must divide the kv block {tk}")

    o = jnp.zeros((b, tq, h, d), jnp.float32)
    m = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tq), jnp.float32)

    q_pos = my_idx * tq + jnp.arange(tq)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        # Which global block this device currently holds: blocks rotate
        # forward, so at step i we hold block (my_idx - i) mod ring.
        kv_idx = (my_idx - i) % axis_size
        k0 = kv_idx * tk
        if kv_chunk is None:
            o, m, l = _accumulate_block(
                q, k_cur, v_cur, q_pos, k0 + jnp.arange(tk), o, m, l,
                scale, causal,
            )
        else:
            def chunk_step(inner, j):
                o, m, l = inner
                k_blk = lax.dynamic_slice_in_dim(k_cur, j * kv_chunk, kv_chunk, 1)
                v_blk = lax.dynamic_slice_in_dim(v_cur, j * kv_chunk, kv_chunk, 1)
                k_pos = k0 + j * kv_chunk + jnp.arange(kv_chunk)
                return _accumulate_block(
                    q, k_blk, v_blk, q_pos, k_pos, o, m, l, scale, causal
                ), None

            (o, m, l), _ = lax.scan(
                chunk_step, (o, m, l), jnp.arange(tk // kv_chunk)
            )
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_next, v_next), None

    (o, m, l, _, _), _ = lax.scan(
        step, (o, m, l, k, v), jnp.arange(axis_size)
    )
    l = jnp.maximum(l, 1e-30)  # fully-masked rows (shouldn't occur causally)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "sp",
    batch_spec: Any = ("dp",),
    head_spec: Any = (None,),
    causal: bool = True,
    scale: float | None = None,
    kv_chunk: int | None = None,
) -> jax.Array:
    """Exact attention with the sequence dim sharded over ``seq_axis``.

    q/k/v: [batch, seq, heads, head_dim] global arrays (sharded or to-be-
    sharded per the specs). Returns the attention output with the same
    sharding as q. ``kv_chunk`` (must divide the per-device block) bounds
    per-device score memory to O(Tq * kv_chunk) for long-context blocks.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(*batch_spec, seq_axis, *head_spec, None)
    body = functools.partial(
        _ring_attention_local, axis_name=seq_axis, causal=causal, scale=scale,
        kv_chunk=kv_chunk,
    )
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def reference_attention(q, k, v, causal: bool = True, scale: float | None = None):
    """Single-device exact attention — the correctness oracle for tests."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
