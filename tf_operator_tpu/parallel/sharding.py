"""Sharding-rule helpers: map logical array dimensions to mesh axes.

The pattern (from the public scaling-book recipe): annotate inputs/params
with NamedShardings, let XLA's SPMD partitioner insert the collectives,
constrain intermediates only where XLA needs the hint.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


def named(mesh: Mesh, *spec: Any) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard dim 0 (batch) over the data axis."""
    return NamedSharding(mesh, P(axis))


def shard_batch(mesh: Mesh, batch: Any, axis: str = "dp") -> Any:
    """Place a host batch with dim-0 sharding over the data axis.

    Single-process: a plain device_put. Multi-process (operator-launched
    multi-host jobs): each process contributes its LOCAL batch shard and the
    result is the global array — the per-host-input-pipeline contract of
    multi-host data parallelism (global batch = concat of process batches).
    """
    sharding = batch_sharded(mesh, axis)
    if jax.process_count() > 1:
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(sharding, x), batch
        )
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicate(mesh: Mesh, tree: Any) -> Any:
    sharding = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def sharding_tree_by_rules(
    mesh: Mesh, params: Any, rules: dict[str, tuple], default: tuple = ()
) -> Any:
    """NamedSharding pytree matching ``params``, from path-substring rules.

    ``rules`` maps a substring of the flattened param path (e.g.
    "Dense_0/kernel") to a PartitionSpec tuple; first match wins, unmatched
    params get ``default`` (replicated). A matched rule whose named axes
    cannot tile the leaf (dim not divisible by the mesh-axis size — e.g.
    GQA/MQA kv projections with n_kv_heads < tp, or an odd vocab under
    tp) falls back to replicated for that leaf instead of crashing
    device_put: sharding is a placement optimization, never a
    correctness requirement.
    """

    def spec_for(path, leaf) -> P:
        p = _path_str(path)
        shape = tuple(getattr(leaf, "shape", ()))
        for sub, spec in rules.items():
            if sub not in p:
                continue
            for d, axis in enumerate(spec):
                if axis is None:
                    continue
                size = mesh.shape.get(axis, 1)
                if d >= len(shape) or (size > 1 and shape[d] % size):
                    return P(*default)  # rule can't tile this leaf
            return P(*spec)
        return P(*default)

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, spec_for(path, leaf)), params
    )


def shard_params_by_rules(
    mesh: Mesh, params: Any, rules: dict[str, tuple], default: tuple = ()
) -> Any:
    """Device-put params per the path-substring PartitionSpec rules."""
    shardings = sharding_tree_by_rules(mesh, params, rules, default)
    return jax.tree.map(jax.device_put, params, shardings)


def fsdp_sharding_tree(
    mesh: Mesh, params: Any, axis: str = "fsdp", min_size: int = 2**11
) -> Any:
    """Fully-sharded-data-parallel placement for a param/optimizer pytree.

    Each array's largest dimension divisible by the ``axis`` size is sharded
    over that axis; arrays smaller than ``min_size`` elements (biases, norm
    scales) stay replicated — the per-chip slice would be smaller than the
    collective's cost. This is the TPU analog of the reference era's
    parameter-server state distribution (SURVEY.md §2.9: PS replicas each
    own a shard of the variables, reference pkg/apis/tensorflow/v1alpha2/
    types.go:117-123): parameter and optimizer state live sharded across the
    data-parallel workers, and XLA inserts the all-gather (forward/backward)
    and reduce-scatter (gradient) collectives a PS round-trip performed.
    """
    size = mesh.shape[axis]

    def spec_for(leaf) -> P:
        shape = tuple(getattr(leaf, "shape", ()))
        if not shape or leaf.size < min_size:
            return P()
        for d in sorted(range(len(shape)), key=lambda i: shape[i], reverse=True):
            if shape[d] % size == 0:
                spec: list[Any] = [None] * len(shape)
                spec[d] = axis
                return P(*spec)
        return P()

    return jax.tree.map(lambda l: NamedSharding(mesh, spec_for(l)), params)


def weight_update_shardings(
    mesh: Mesh, opt_state: Any, axis: str = "dp", min_size: int = 2**11
) -> Any:
    """ZeRO-1 / weight-update sharding for PLAIN data parallelism.

    The optimizer STATE (adam mu/nu, etc.) is sharded over the data axis
    while params stay replicated — forward and backward are untouched
    (no FSDP all-gather on the compute path), but moment memory and
    update FLOPs drop by the dp size: GSPMD turns the gradient reduction
    feeding each moment shard into reduce-scatter form and all-gathers
    only the updated param. This is the automatic cross-replica
    weight-update sharding of arXiv:2004.13336, the right point on the
    curve when the model fits replicated but 2x adam moments do not (or
    when FSDP's forward gathers cost more than they save — small models,
    fast steps). Apply via:

        state = TrainState.create(params, tx)           # replicated
        opt_sh = weight_update_shardings(mesh, state.opt_state)
        state = state.replace(opt_state=jax.tree.map(
            jax.device_put, state.opt_state, opt_sh))
        step = make_lm_train_step(..., opt_shardings=opt_sh)

    The step pins params REPLICATED by default when opt_shardings is set
    and param_shardings is not: without that pin GSPMD would propagate
    the sharded update into new_params (silent FSDP) instead of
    all-gathering it.

    Same per-leaf placement rule as fsdp_sharding_tree (largest divisible
    dim; small leaves and scalars — counts — stay replicated)."""
    return fsdp_sharding_tree(mesh, opt_state, axis=axis, min_size=min_size)


def shard_params_fsdp(
    mesh: Mesh, params: Any, axis: str = "fsdp", min_size: int = 2**11
) -> Any:
    """Device-put params with fsdp placement (see fsdp_sharding_tree).

    Call BEFORE ``tx.init`` so optimizer moments inherit the sharded
    placement — that is what makes optimizer state fully sharded too.
    """
    shardings = fsdp_sharding_tree(mesh, params, axis, min_size)
    return jax.tree.map(jax.device_put, params, shardings)


def constrain(x: Any, mesh: Mesh, *spec: Any) -> Any:
    """with_sharding_constraint shorthand for intermediates inside jit."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))
