"""Sharding-rule helpers: map logical array dimensions to mesh axes.

The pattern (from the public scaling-book recipe): annotate inputs/params
with NamedShardings, let XLA's SPMD partitioner insert the collectives,
constrain intermediates only where XLA needs the hint.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


def named(mesh: Mesh, *spec: Any) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard dim 0 (batch) over the data axis."""
    return NamedSharding(mesh, P(axis))


def shard_batch(mesh: Mesh, batch: Any, axis: str = "dp") -> Any:
    """Device-put a host batch with dim-0 sharding over the data axis."""
    sharding = batch_sharded(mesh, axis)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicate(mesh: Mesh, tree: Any) -> Any:
    sharding = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def shard_params_by_rules(
    mesh: Mesh, params: Any, rules: dict[str, tuple], default: tuple = ()
) -> Any:
    """Apply PartitionSpec rules keyed by parameter-path substring.

    ``rules`` maps a substring of the flattened param path (e.g. "Dense_0/kernel")
    to a PartitionSpec tuple; first match wins, unmatched params get ``default``
    (replicated). Returns the device-put params.
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def path_str(path) -> str:
        return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)

    def spec_for(path) -> P:
        p = path_str(path)
        for sub, spec in rules.items():
            if sub in p:
                return P(*spec)
        return P(*default)

    placed = {
        path_str(path): jax.device_put(leaf, NamedSharding(mesh, spec_for(path)))
        for path, leaf in flat
    }
    # Rebuild the tree in place.
    def rebuild(path, leaf):
        return placed[path_str(path)]

    return jax.tree_util.tree_map_with_path(rebuild, params)


def constrain(x: Any, mesh: Mesh, *spec: Any) -> Any:
    """with_sharding_constraint shorthand for intermediates inside jit."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))
