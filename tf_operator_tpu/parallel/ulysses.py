"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second long-context strategy next to ring attention (the goal calls
for "ring attention or all-to-all sequence/context parallelism"; this
framework ships both, selectable per model via
``TransformerConfig.ring_impl="ulysses"``):

- input activations arrive sequence-sharded: [B, S/sp, H, D] per device
  (the same layout ring attention uses, so the two strategies are
  drop-in interchangeable);
- one ``lax.all_to_all`` re-shards heads instead: [B, S, H/sp, D] — each
  device now holds the FULL sequence for its head group, so plain
  (flash-kernel) attention runs locally with exact causal masking and no
  per-step ring latency;
- a second all_to_all restores the sequence sharding for the projections
  that follow.

Trade-off vs ring (jax-ml scaling-book framing): Ulysses moves O(B*S*H*D)
bytes twice per layer in two bursts and computes with zero inner-loop
communication — better when heads >= sp and the interconnect favors
all-to-all; ring pipelines O(S^2) compute against sp hops of K/V — the
only option when sp exceeds the head count. Both are exact.

Requires (local heads) % sp == 0 (composes with tp on the head axis:
requirement becomes (H/tp) % sp == 0) and S % sp == 0.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from tf_operator_tpu import parallel as parallel_compat


def _ulysses_local(q, k, v, *, seq_axis: str, causal: bool,
                   scale: float | None, use_flash: bool | None):
    from tf_operator_tpu.ops import attention as device_attention

    # [B, S/sp, H, D] -> [B, S, H/sp, D]: split heads, concat sequence.
    a2a = lambda x: lax.all_to_all(  # noqa: E731
        x, seq_axis, split_axis=2, concat_axis=1, tiled=True
    )
    qf, kf, vf = a2a(q), a2a(k), a2a(v)
    # use_flash=None defers to attention_kernel() dispatch, so the
    # TPU_OPERATOR_ATTN A/B override and the off-TPU XLA fallback are
    # honored here exactly as on the single-device path.
    out = device_attention(
        qf, kf, vf, causal=causal, scale=scale, use_flash=use_flash
    )
    # [B, S, H/sp, D] -> [B, S/sp, H, D]: the inverse exchange.
    return lax.all_to_all(
        out, seq_axis, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "sp",
    batch_spec: Any = (None,),
    head_spec: Any = (None,),
    causal: bool = True,
    scale: float | None = None,
    use_flash: bool | None = None,
) -> jax.Array:
    """Exact attention with the sequence dim sharded over ``seq_axis``,
    computed via head/sequence all-to-all. Same signature family as
    ``ring_attention`` so callers can switch strategies freely.

    q, k, v: [batch, seq, heads, head_dim] with seq sharded over
    ``seq_axis`` (and optionally batch over ``batch_spec`` axes, heads
    over ``head_spec`` axes, e.g. tp).
    """
    sp = mesh.shape[seq_axis]
    B, S, H, D = q.shape
    if S % sp:
        raise ValueError(f"seq {S} not divisible by {seq_axis}={sp}")
    # Heads available locally after any head_spec (tp) sharding.
    tp_total = 1
    for ax in head_spec:
        if ax is not None:
            tp_total *= mesh.shape[ax]
    if (H // tp_total) % sp:
        raise ValueError(
            f"local heads {H // tp_total} not divisible by {seq_axis}={sp} "
            "— use ring attention for sp beyond the head count"
        )
    spec = P(*batch_spec, seq_axis, *head_spec, None)
    import functools

    body = functools.partial(
        _ulysses_local, seq_axis=seq_axis, causal=causal, scale=scale,
        use_flash=use_flash,
    )
    return parallel_compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
