"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The ``pp`` mesh axis holds one pipeline stage per device group; activations
hop stage-to-stage over ICI via ``lax.ppermute`` while ``lax.scan`` drives
the microbatch schedule — compiler-friendly (static trip count, no Python
control flow under jit) and differentiable end-to-end (autodiff through
scan + ppermute + psum gives the reverse pipeline schedule for free).

The reference has no model parallelism of any kind (SURVEY.md §2.9); this
is part of the TPU-native capability layer the rebuild adds. Design follows
the public scaling-book recipe: put the loop *inside* shard_map so XLA sees
per-device code with explicit collectives.

Schedule: with S stages and M microbatches the scan runs M+S-1 ticks; at
tick t stage 0 ingests microbatch t (t < M) while stage s computes the
activation that left stage 0 at tick t-s. Valid last-stage outputs appear
at ticks S-1 .. S+M-2 and are broadcast to all stages with a masked psum
(cheap at these sizes; callers that shard the batch too can slice instead).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P


def stack_stage_params(param_list: list[Any]) -> Any:
    """Stack per-stage param pytrees into one pytree with leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "pp",
    batch_axis: str | None = None,
) -> jax.Array:
    """Run microbatches through S pipelined stages sharded over ``axis``.

    stage_fn: (one stage's params, activation) -> activation (same shape).
    stage_params: pytree whose leaves have leading dim S (stage); sharded
      over ``axis`` so each device group holds exactly its stage's weights.
    microbatches: [M, microbatch, ...] input activations.
    batch_axis: optionally also shard the microbatch dim (dim 1) over a
      data axis — each dp group runs an independent pipeline replica on its
      batch shard (pp x dp composition; stage-param grads are summed over
      dp by shard_map's reverse transfer).
    Returns [M, microbatch, ...] outputs of the final stage (replicated
    over ``axis``, batch-sharded over ``batch_axis`` if given).
    """
    n_stages = mesh.shape[axis]
    num_micro = microbatches.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != {axis} axis "
                f"size {n_stages}; to run multiple layers per stage, fold "
                "them into stage_fn (a silent mismatch would drop stages)"
            )

    def local(params, x):
        # params leaves arrive as [1, ...] (this device's stage); unstack.
        p = jax.tree.map(lambda a: a[0], params)
        stage = lax.axis_index(axis)

        def tick(state, t):
            prev = lax.ppermute(state, axis, perm)  # stage s-1's last output
            fresh = x[jnp.clip(t, 0, num_micro - 1)]
            inp = jnp.where(stage == 0, fresh, prev)
            out = stage_fn(p, inp)
            return out, out

        _, outs = lax.scan(
            tick, jnp.zeros_like(x[0]), jnp.arange(num_micro + n_stages - 1)
        )
        # Ticks S-1 .. S+M-2 of the LAST stage are the pipeline's outputs.
        valid = lax.dynamic_slice_in_dim(outs, n_stages - 1, num_micro, 0)
        valid = jnp.where(stage == n_stages - 1, valid, 0)
        return lax.psum(valid, axis)

    data_spec = P(None, batch_axis) if batch_axis else P()
    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), data_spec),
        out_specs=data_spec,
        check_vma=False,
    )(stage_params, microbatches)


def microbatch(x: jax.Array, num_micro: int) -> jax.Array:
    """[batch, ...] -> [num_micro, batch/num_micro, ...]."""
    if x.shape[0] % num_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {num_micro} microbatches"
        )
    return x.reshape(num_micro, x.shape[0] // num_micro, *x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    """[num_micro, mb, ...] -> [num_micro*mb, ...]."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])
