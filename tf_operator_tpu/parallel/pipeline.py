"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The ``pp`` mesh axis holds one pipeline stage per device group; activations
hop stage-to-stage over ICI via ``lax.ppermute`` while ``lax.scan`` drives
the microbatch schedule — compiler-friendly (static trip count, no Python
control flow under jit) and differentiable end-to-end (autodiff through
scan + ppermute + psum gives the reverse pipeline schedule for free).

The reference has no model parallelism of any kind (SURVEY.md §2.9); this
is part of the TPU-native capability layer the rebuild adds. Design follows
the public scaling-book recipe: put the loop *inside* shard_map so XLA sees
per-device code with explicit collectives.

Schedule: with S stages and M microbatches the scan runs M+S-1 ticks; at
tick t stage 0 ingests microbatch t (t < M) while stage s computes the
activation that left stage 0 at tick t-s. Valid last-stage outputs appear
at ticks S-1 .. S+M-2 and are broadcast to all stages with a masked psum
(cheap at these sizes; callers that shard the batch too can slice instead).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from tf_operator_tpu import parallel as parallel_compat


def stack_stage_params(param_list: list[Any]) -> Any:
    """Stack per-stage param pytrees into one pytree with leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "pp",
    batch_axis: str | None = None,
) -> jax.Array:
    """Run microbatches through S pipelined stages sharded over ``axis``.

    stage_fn: (one stage's params, activation) -> activation (same shape).
    stage_params: pytree whose leaves have leading dim S (stage); sharded
      over ``axis`` so each device group holds exactly its stage's weights.
    microbatches: [M, microbatch, ...] input activations.
    batch_axis: optionally also shard the microbatch dim (dim 1) over a
      data axis — each dp group runs an independent pipeline replica on its
      batch shard (pp x dp composition; stage-param grads are summed over
      dp by shard_map's reverse transfer).
    Returns [M, microbatch, ...] outputs of the final stage (replicated
    over ``axis``, batch-sharded over ``batch_axis`` if given).
    """
    n_stages = mesh.shape[axis]
    num_micro = microbatches.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != {axis} axis "
                f"size {n_stages}; to run multiple layers per stage, fold "
                "them into stage_fn (a silent mismatch would drop stages)"
            )

    def local(params, x):
        # params leaves arrive as [1, ...] (this device's stage); unstack.
        p = jax.tree.map(lambda a: a[0], params)
        stage = lax.axis_index(axis)

        def tick(state, t):
            prev = lax.ppermute(state, axis, perm)  # stage s-1's last output
            fresh = x[jnp.clip(t, 0, num_micro - 1)]
            inp = jnp.where(stage == 0, fresh, prev)
            out = stage_fn(p, inp)
            return out, out

        _, outs = lax.scan(
            tick, jnp.zeros_like(x[0]), jnp.arange(num_micro + n_stages - 1)
        )
        # Ticks S-1 .. S+M-2 of the LAST stage are the pipeline's outputs.
        valid = lax.dynamic_slice_in_dim(outs, n_stages - 1, num_micro, 0)
        valid = jnp.where(stage == n_stages - 1, valid, 0)
        return lax.psum(valid, axis)

    data_spec = P(None, batch_axis) if batch_axis else P()
    return parallel_compat.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), data_spec),
        out_specs=data_spec,
        check_vma=False,
    )(stage_params, microbatches)


def pipeline_value_and_grad(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    last_fn: Callable[[Any, jax.Array, jax.Array], jax.Array],
    mesh: Mesh,
    *,
    axis: str = "pp",
    batch_axis: str | None = None,
) -> Callable[[Any, Any, jax.Array, jax.Array], tuple]:
    """1F1B pipelined training step: loss AND grads in one schedule.

    ``pipeline_apply`` + autodiff is GPipe: all M forwards run, then all M
    backwards, and the scan stores O(M) microbatch activations per stage.
    This engine interleaves them (the 1F1B family): each scan tick moves
    one forward activation down-pipe and one cotangent up-pipe via
    ``lax.ppermute``, the loss head runs in-pipeline on the last stage so
    microbatch i's backward starts the tick after its forward finishes,
    and the pipeline-internal stash is a ring of 2S microbatch inputs —
    O(S), independent of M. That is the property that matters: the GPipe
    bubble is (S-1)/M of the step, so you shrink it by raising M, and with
    this engine raising M no longer raises activation memory. Backward
    ticks recompute the stage forward (jax.vjp over the stashed input) —
    the same FLOPs as GPipe-with-remat (2 fwd + 1 bwd per microbatch).

    Schedule arithmetic (stage s of S, microbatch i of M, R = 2S ring):
      fwd tick  t_f(s, i) = s + i           (GPipe-timed forwards)
      bwd tick  t_b(s, i) = 2S - 1 - s + i  (cotangent arrives up-pipe)
      total ticks T = M + 2S - 1; in-flight stash <= 2S - 1 < R.
    Every tick executes both branches masked (SPMD lockstep): warmup /
    drain ticks waste the masked branch — the (2S-2)/M bubble — and the
    masked last_fn costs what GPipe's outside-the-pipeline head (also
    replicated over pp) pays anyway.

    stage_fn: (stage params, activation [mb, ...]) -> activation.
    last_fn: (last params, activation, targets [mb, ...]) -> scalar mean
      loss for that microbatch (e.g. final norm + vocab head + xent).
    Returns run(stage_params, last_params, microbatches, targets) ->
      (loss, stage_grads, last_grads, d_microbatches): loss is the global
      mean; stage_grads matches stage_params ([S, ...] leaves, sharded
      over ``axis``); d_microbatches feeds the caller's embedding vjp.
    """
    n_stages = mesh.shape[axis]
    n_dp = mesh.shape[batch_axis] if batch_axis else 1

    def run(stage_params, last_params, microbatches, targets):
        num_micro = microbatches.shape[0]
        S, M, R = n_stages, num_micro, 2 * n_stages
        T = M + 2 * S - 1
        perm_dn = [(i, (i + 1) % S) for i in range(S)]
        perm_up = [(i, (i - 1) % S) for i in range(S)]
        seed = 1.0 / (M * n_dp)  # each microbatch-mean's weight in the
        # global mean loss; seeding the head vjp with it makes every
        # accumulated grad exact with no post-scaling.

        def local(sp, lp, x, tgt):
            p = jax.tree.map(lambda a: a[0], sp)
            stage = lax.axis_index(axis)
            is_last = stage == S - 1
            is_first = stage == 0
            zero_act = jnp.zeros_like(x[0])
            carry0 = dict(
                fwd_msg=zero_act,
                bwd_msg=zero_act,
                x_stash=jnp.zeros((R,) + x.shape[1:], x.dtype),
                dy_stash=jnp.zeros((R,) + x.shape[1:], x.dtype),
                gp=jax.tree.map(jnp.zeros_like, p),
                gl=jax.tree.map(jnp.zeros_like, lp),
                loss=jnp.zeros((), jnp.float32),
                dx_out=jnp.zeros_like(x),
            )

            def tick(c, t):
                fwd_in = lax.ppermute(c["fwd_msg"], axis, perm_dn)
                bwd_in = lax.ppermute(c["bwd_msg"], axis, perm_up)
                # --- forward branch: microbatch i_f enters this stage ---
                i_f = t - stage
                f_valid = (i_f >= 0) & (i_f < M)
                i_fc = jnp.clip(i_f, 0, M - 1)
                xf = jnp.where(is_first, x[i_fc], fwd_in)
                xf = jnp.where(f_valid, xf, 0)  # masked ticks compute on 0s
                y = stage_fn(p, xf)
                # Last stage: head + loss + its vjp IN the same tick, so
                # the backward can start next tick (this is what makes it
                # 1F1B rather than fwd-all-then-bwd-all).
                loss_i, head_vjp = jax.vjp(
                    lambda lp_, y_: last_fn(lp_, y_, tgt[i_fc]), lp, y
                )
                dlp_i, dy_i = head_vjp(jnp.asarray(seed, loss_i.dtype))
                take_loss = f_valid & is_last
                w_loss = jnp.where(take_loss, 1.0, 0.0)
                loss = c["loss"] + w_loss * loss_i.astype(jnp.float32)
                gl = jax.tree.map(
                    lambda a, g: a + w_loss.astype(a.dtype) * g,
                    c["gl"], dlp_i,
                )
                # Ring stashes (masked writes keep live slots intact; a
                # fwd write and the bwd read below always hit different
                # slots: i_f - i_b = 2S-1-2s is odd, R is even).
                slot_f = jnp.mod(i_fc, R)
                old_x = lax.dynamic_index_in_dim(
                    c["x_stash"], slot_f, 0, keepdims=False)
                x_stash = lax.dynamic_update_index_in_dim(
                    c["x_stash"], jnp.where(f_valid, xf, old_x), slot_f, 0)
                old_dy = lax.dynamic_index_in_dim(
                    c["dy_stash"], slot_f, 0, keepdims=False)
                dy_stash = lax.dynamic_update_index_in_dim(
                    c["dy_stash"],
                    jnp.where(take_loss, dy_i.astype(x.dtype), old_dy),
                    slot_f, 0)
                # --- backward branch: microbatch i_b leaves this stage ---
                i_b = t - (2 * S - 1 - stage)
                b_valid = (i_b >= 0) & (i_b < M)
                i_bc = jnp.clip(i_b, 0, M - 1)
                slot_b = jnp.mod(i_bc, R)
                xb = lax.dynamic_index_in_dim(
                    x_stash, slot_b, 0, keepdims=False)
                dyb = lax.dynamic_index_in_dim(
                    dy_stash, slot_b, 0, keepdims=False)
                cot = jnp.where(is_last, dyb, bwd_in)
                cot = jnp.where(b_valid, cot, 0)
                # Recompute-and-pull-back (stage-granular remat): only
                # this tick's intermediates live, never a whole pipeline's.
                _, stage_vjp = jax.vjp(stage_fn, p, xb)
                dp_i, dx_i = stage_vjp(cot)
                w_b = jnp.where(b_valid, 1.0, 0.0)
                gp = jax.tree.map(
                    lambda a, g: a + w_b.astype(a.dtype) * g, c["gp"], dp_i)
                dx_out = c["dx_out"].at[i_bc].add(
                    jnp.where(b_valid & is_first, dx_i, 0))
                return dict(fwd_msg=y, bwd_msg=dx_i, x_stash=x_stash,
                            dy_stash=dy_stash, gp=gp, gl=gl, loss=loss,
                            dx_out=dx_out), None

            c, _ = lax.scan(tick, carry0, jnp.arange(T))
            gp, gl, loss = c["gp"], c["gl"], c["loss"]
            if batch_axis and n_dp > 1:
                gp = lax.psum(gp, batch_axis)
                gl = lax.psum(gl, batch_axis)
                loss = lax.psum(loss, batch_axis)
            # Only the last stage accumulated loss/head grads; only stage
            # 0 accumulated input cotangents — masked psums broadcast them.
            gl = lax.psum(gl, axis)
            loss = lax.psum(loss, axis) * seed
            dx_out = lax.psum(c["dx_out"], axis)
            return (loss, jax.tree.map(lambda a: a[None], gp), gl, dx_out)

        data_spec = P(None, batch_axis) if batch_axis else P()
        return parallel_compat.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(), data_spec, data_spec),
            out_specs=(P(), P(axis), P(), data_spec),
            check_vma=False,
        )(stage_params, last_params, microbatches, targets)

    return run


def microbatch(x: jax.Array, num_micro: int) -> jax.Array:
    """[batch, ...] -> [num_micro, batch/num_micro, ...]."""
    if x.shape[0] % num_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {num_micro} microbatches"
        )
    return x.reshape(num_micro, x.shape[0] // num_micro, *x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    """[num_micro, mb, ...] -> [num_micro*mb, ...]."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])
