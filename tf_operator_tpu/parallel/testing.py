"""Helpers for running the parallel stack on a virtual CPU mesh.

Import `force_cpu_mesh()` BEFORE any other jax usage in a script to get an
8-device CPU platform regardless of what platform plugin the environment
pins (needed because some TPU plugin environments re-export JAX_PLATFORMS).

CPU-backend caveat for collective-heavy train loops: the in-process
communicator can DEADLOCK (rendezvous termination timeout, process abort)
when many async dispatches of a cross-module-collective executable overlap
— observed with fsdp all-gather/reduce-scatter programs after ~100
unserialized steps. Read a metric back (``float(metrics["loss"])``) each
iteration in CPU-mesh loops; on real TPU the per-device stream serializes
executions and the issue cannot occur.
"""

from __future__ import annotations

import os


def force_cpu_mesh(n_devices: int = 8) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:  # no-op if the backend is already initialized
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
