"""Parallelism primitives (mesh, sharding, ring/ulysses attention, pipeline).

Also the jax version-compat seam: `shard_map` was promoted from
`jax.experimental.shard_map` to `jax.shard_map` (and its `check_rep` kwarg
renamed `check_vma`) around 0.5/0.6; the graft toolchain pins 0.4.x. Import
it from here so every caller — written against the modern spelling — runs
on both.
"""

import jax as _jax

try:
    shard_map = _jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, /, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _legacy_shard_map(f, *args, **kwargs)
