"""Device-mesh construction for SPMD training.

The TPU-native replacement for the reference's PS/Worker process topology:
instead of a cluster-spec of gRPC servers, parallelism is a
``jax.sharding.Mesh`` over the slice's devices with named axes, and XLA
inserts the collectives (the "pick a mesh, annotate shardings" recipe).

Axis conventions used across the framework:

- ``dcn`` — cross-slice data parallelism (multislice: gradients reduced
          over the data-center network between slices; always the
          outermost axis so in-slice collectives ride ICI)
- ``dp``  — data parallelism (batch split; gradients all-reduced over ICI)
- ``fsdp``— data parallelism with sharded parameters/optimizer state
          (the TPU analog of the reference era's "PS sharding": parameter
          state lives sharded across data-parallel workers)
- ``tp``  — tensor parallelism (feature/head split inside a layer)
- ``sp``  — sequence/context parallelism (ring attention over this axis)
- ``pp``  — pipeline parallelism (layer stages)
- ``ep``  — expert parallelism (MoE expert split)

Reference parity note: the reference itself has no sharded execution
(SURVEY.md §2.9) — the cluster topology it wires up (PS/Worker over
TF_CONFIG) is superseded by these mesh axes on TPU.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("dcn", "pp", "dp", "fsdp", "ep", "sp", "tp")


def create_mesh(
    axes: dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh with the given axis sizes over the given devices.

    Axis sizes of 1 are kept (so downstream PartitionSpecs can always name
    the axis); a single ``-1`` axis absorbs the remaining devices.

    >>> mesh = create_mesh({"dp": -1, "tp": 2})
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(axes or {"dp": n})

    wildcard = [k for k, v in axes.items() if v == -1]
    if len(wildcard) > 1:
        raise ValueError("at most one axis may be -1")
    fixed = math.prod(v for v in axes.values() if v != -1)
    if wildcard:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes {axes}")
        axes[wildcard[0]] = n // fixed
    elif fixed != n:
        raise ValueError(f"mesh axes {axes} need {fixed} devices, have {n}")

    names = tuple(sorted(axes, key=lambda a: AXIS_ORDER.index(a) if a in AXIS_ORDER else 99))
    shape = tuple(axes[a] for a in names)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def slice_mesh(accelerator_type: str, topology: str | None = None,
               devices: Sequence[jax.Device] | None = None,
               data_axis: str = "dp") -> Mesh:
    """Data-parallel mesh over exactly one TPU slice.

    Validates that the visible device count matches the slice's device count
    (catching "ran a v5e-16 job on a v5e-8 reservation" misconfigurations at
    mesh-build time), then returns a 1-axis data mesh. For model-parallel
    layouts over the slice, pass the validated device list to create_mesh
    with the axis split you want.
    """
    from tf_operator_tpu.topology import slices

    topo = slices.resolve(accelerator_type, topology)
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) != topo.num_devices:
        raise ValueError(
            f"slice {topo.accelerator_type} has {topo.num_devices} devices "
            f"but {len(devices)} are visible"
        )
    return create_mesh({data_axis: len(devices)}, devices)


def multislice_mesh(
    num_slices: int,
    axes: dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Mesh for a MEGASCALE multislice job: ``dcn`` (cross-slice, outermost)
    x the per-slice axes.

    The per-slice ``axes`` (default all-dp) describe ONE slice; the device
    count must be num_slices x their product. On real multislice hardware
    jax.devices() orders devices slice-major (slice id is part of the device
    coords), so the outermost-dcn reshape puts each slice's devices in one
    dcn row and every non-dcn collective stays on ICI; gradient all-reduce
    over dcn is the only DCN traffic — the operator's MEGASCALE env
    (controller/cluster_spec.py gen_tpu_env) is what wires the slices'
    runtimes together underneath.
    """
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) % num_slices:
        raise ValueError(f"{len(devices)} devices not divisible into {num_slices} slices")
    per_slice = len(devices) // num_slices
    axes = dict(axes or {"dp": per_slice})
    if math.prod(axes.values()) != per_slice:
        raise ValueError(f"per-slice axes {axes} need {per_slice} devices/slice")
    return create_mesh({"dcn": num_slices, **axes}, devices)


def host_local_batch_size(global_batch: int, mesh: Mesh, axis: str = "dp") -> int:
    size = mesh.shape.get(axis, 1)
    if global_batch % size:
        raise ValueError(f"global batch {global_batch} not divisible by {axis}={size}")
    return global_batch // size
