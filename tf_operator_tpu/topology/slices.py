"""TPU pod-slice topology math.

This is the module that makes the accelerator a first-class scheduling object.
The reference handles accelerators as opaque container resource limits plus
config-file volume injection (helper.ConfigureAcceleratorsForTFJobSpec,
pkg/apis/tensorflow/helper/helpers.go:50-104); a TPU slice instead has
structure the controller must understand: a slice of N chips spans M hosts
connected by ICI, every host must run exactly one worker pod, and all hosts
must be gang-scheduled or the slice is stranded.

Naming follows Cloud TPU conventions: an *accelerator type* like ``v5e-16``
is (generation, total chip count); a *topology* like ``4x4`` is the physical
chip arrangement.  ``num_hosts`` is what the controller actually schedules.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


@dataclass(frozen=True)
class TPUGeneration:
    """Per-generation constants."""

    name: str
    # Chips addressable by one host VM (one worker process per host).
    chips_per_host: int
    # Cores exposed per chip (v4/v5p megacore presents 1 device per chip).
    devices_per_chip: int
    # Largest single slice offered.
    max_chips: int
    # K8s node selector value (GKE convention: cloud.google.com/gke-tpu-accelerator).
    gke_accelerator: str
    # Dimensionality of the ICI torus for default topology inference.
    torus_dims: int = 2


GENERATIONS: dict[str, TPUGeneration] = {
    "v4": TPUGeneration("v4", 4, 1, 4096, "tpu-v4-podslice", torus_dims=3),
    "v5e": TPUGeneration("v5e", 4, 1, 256, "tpu-v5-lite-podslice", torus_dims=2),
    "v5p": TPUGeneration("v5p", 4, 1, 8960, "tpu-v5p-slice", torus_dims=3),
    "v6e": TPUGeneration("v6e", 4, 1, 256, "tpu-v6e-slice", torus_dims=2),
}

# Topologies that fit on a single host (no ICI-spanning pods needed); a
# single-host slice may be scheduled without gang semantics.
_SINGLE_HOST_MAX_CHIPS = {"v4": 4, "v5e": 8, "v5p": 4, "v6e": 8}


class TopologyError(ValueError):
    """Raised for accelerator types / topologies the fleet does not offer."""


@dataclass(frozen=True)
class SliceTopology:
    """A resolved TPU pod-slice shape.

    The controller consumes ``num_hosts`` (pod count) and the env-injection
    layer consumes ``topology``/``accelerator_type`` (runtime mesh wiring).
    """

    accelerator_type: str  # e.g. "v5e-16"
    generation: str  # "v5e"
    num_chips: int  # 16
    topology: str  # "4x4"
    num_hosts: int  # 4
    chips_per_host: int  # 4
    dims: tuple[int, ...] = field(default_factory=tuple)

    @property
    def num_devices(self) -> int:
        gen = GENERATIONS[self.generation]
        return self.num_chips * gen.devices_per_chip

    @property
    def multi_host(self) -> bool:
        return self.num_hosts > 1

    @property
    def gke_accelerator(self) -> str:
        return GENERATIONS[self.generation].gke_accelerator


def parse_accelerator_type(accelerator_type: str) -> tuple[str, int]:
    """Split ``"v5e-16"`` into ``("v5e", 16)``."""
    parts = accelerator_type.strip().lower().split("-")
    if len(parts) != 2 or parts[0] not in GENERATIONS:
        raise TopologyError(
            f"unknown accelerator type {accelerator_type!r}; expected "
            f"<generation>-<chips> with generation in {sorted(GENERATIONS)}"
        )
    try:
        chips = int(parts[1])
    except ValueError as e:
        raise TopologyError(f"bad chip count in {accelerator_type!r}") from e
    if chips <= 0:
        raise TopologyError(f"chip count must be positive in {accelerator_type!r}")
    gen = GENERATIONS[parts[0]]
    if chips > gen.max_chips:
        raise TopologyError(
            f"{accelerator_type!r}: {chips} chips exceeds the {gen.name} "
            f"maximum of {gen.max_chips}"
        )
    return parts[0], chips


def _default_dims(chips: int, ndims: int) -> tuple[int, ...]:
    """Most-square factorization of ``chips`` into ``ndims`` power-of-two-ish dims."""
    if ndims == 2:
        a = 1
        for cand in range(int(math.isqrt(chips)), 0, -1):
            if chips % cand == 0:
                a = cand
                break
        return (a, chips // a)
    # 3D: peel off the most-cubic factor triple.
    best = (1, 1, chips)
    best_score = chips
    for x in range(1, int(round(chips ** (1 / 3))) + 2):
        if chips % x:
            continue
        rest = chips // x
        for y in range(x, int(math.isqrt(rest)) + 1):
            if rest % y:
                continue
            z = rest // y
            score = z - x
            if score < best_score:
                best, best_score = (x, y, z), score
    return best


def parse_topology(topology: str) -> tuple[int, ...]:
    """Parse ``"4x4"`` / ``"2x2x4"`` into dims."""
    try:
        dims = tuple(int(d) for d in topology.lower().split("x"))
    except ValueError as e:
        raise TopologyError(f"bad topology string {topology!r}") from e
    if not dims or any(d <= 0 for d in dims):
        raise TopologyError(f"bad topology string {topology!r}")
    return dims


def resolve(accelerator_type: str, topology: str | None = None) -> SliceTopology:
    """Resolve an accelerator type (+ optional explicit topology) to a slice shape.

    >>> resolve("v5e-16").num_hosts
    4
    """
    gen_name, chips = parse_accelerator_type(accelerator_type)
    gen = GENERATIONS[gen_name]
    if topology:
        dims = parse_topology(topology)
        if math.prod(dims) != chips:
            raise TopologyError(
                f"topology {topology!r} has {math.prod(dims)} chips but "
                f"accelerator {accelerator_type!r} declares {chips}"
            )
    else:
        dims = _default_dims(chips, gen.torus_dims)

    if chips <= _SINGLE_HOST_MAX_CHIPS[gen_name]:
        num_hosts = 1
        chips_per_host = chips
    else:
        if chips % gen.chips_per_host:
            raise TopologyError(
                f"{accelerator_type!r}: multi-host slices must be a multiple "
                f"of {gen.chips_per_host} chips/host"
            )
        num_hosts = chips // gen.chips_per_host
        chips_per_host = gen.chips_per_host

    return SliceTopology(
        accelerator_type=f"{gen_name}-{chips}",
        generation=gen_name,
        num_chips=chips,
        topology="x".join(str(d) for d in dims),
        num_hosts=num_hosts,
        chips_per_host=chips_per_host,
        dims=dims,
    )


def catalog(max_chips: int = 256) -> list[dict]:
    """Enumerate the offerable slice shapes (for pickers/UIs).

    One entry per (generation, power-of-two chip count up to max_chips and
    the generation's own limit) with its default topology and host count —
    the data behind the dashboard's accelerator dropdown (the TPU-native
    version of the reference's GPU form fields, CreateJob.jsx).
    """
    out: list[dict] = []
    for gen_name in sorted(GENERATIONS):
        gen = GENERATIONS[gen_name]
        chips = 1
        while chips <= min(max_chips, gen.max_chips):
            if chips >= gen.chips_per_host or chips in (1, 2, 4):
                try:
                    topo = resolve(f"{gen_name}-{chips}")
                except TopologyError:
                    chips *= 2
                    continue
                out.append(
                    {
                        "acceleratorType": topo.accelerator_type,
                        "topology": topo.topology,
                        "numChips": topo.num_chips,
                        "numHosts": topo.num_hosts,
                        "multiHost": topo.multi_host,
                    }
                )
            chips *= 2
    return out
