"""E2E test driver: deploy a TPUJob, drive its lifecycle (including fault
injection against a live replica), assert the outcome, emit JUnit XML.

Parity: py/test_runner.py — the reference's CI driver (run_test:373-585):
deploy via ksonnet, wait for Running, `terminateReplica` through the
apiserver service proxy (:285-318), event-based pod/service accounting
(:217-281), repeat trials, delete + wait-for-GC, junit output. This version
drives any runtime exposing the framework's REST API; fault injection
reaches the fake-workload server (harness/test_server.py) at the address
the executor publishes in pod status (the service-proxy analog).

  python -m tf_operator_tpu.harness.test_runner \
      --master http://127.0.0.1:8080 --shutdown-policy worker \
      --trials 2 --junit-path /tmp/junit.xml
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import JobConditionType
from tf_operator_tpu.client import TPUJobClient
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ClusterClient
from tf_operator_tpu.utils import logger

from tf_operator_tpu.harness import junit

LOG = logger.with_fields(component="test-runner")


class TestFailure(AssertionError):
    pass


def _http_get_json(url: str, timeout: float = 10.0, retry_for: float = 45.0) -> dict:
    """GET with retry on connection refusal: a pod can be Running before its
    server has bound the port (same race the reference absorbs with its
    retrying service-proxy polls). The budget is generous — under CI the
    replica interpreter starts while parallel workflow steps compete for
    CPU, and a too-small window flakes."""
    deadline = time.monotonic() + retry_for
    while True:
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return json.loads(resp.read())
        except (ConnectionError, urllib.error.URLError) as e:
            if time.monotonic() >= deadline:
                raise TestFailure(f"GET {url} failed after retries: {e}") from e
            time.sleep(0.2)


# ---------------------------------------------------------------------------
# Replica fault injection (terminateReplica analog)
# ---------------------------------------------------------------------------

def replica_address(
    client: ClusterClient, namespace: str, job_name: str, rtype: str, index: int
) -> tuple[str, int]:
    """Address of one replica, from executor-published pod status."""
    pods = client.list(
        objects.PODS,
        namespace,
        label_selector={
            constants.LABEL_JOB_NAME: job_name,
            constants.LABEL_REPLICA_TYPE: rtype.lower(),
            constants.LABEL_REPLICA_INDEX: str(index),
        },
    )
    if not pods:
        raise TestFailure(f"no pod for {job_name} {rtype}:{index}")
    status = pods[0].get("status", {})
    ip, port = status.get("podIP"), status.get("hostPort")
    if not ip or not port:
        raise TestFailure(
            f"pod {objects.name_of(pods[0])} has no published address "
            f"(phase={status.get('phase')})"
        )
    return ip, int(port)


def terminate_replica(
    client: ClusterClient,
    namespace: str,
    job_name: str,
    rtype: str,
    index: int = 0,
    exit_code: int = 0,
    timeout: float = 10.0,
) -> None:
    """GET /exit?exitCode=n on a replica's test server
    (test_runner.py:285-318 analog)."""
    ip, port = replica_address(client, namespace, job_name, rtype, index)
    url = f"http://{ip}:{port}/exit?exitCode={exit_code}"
    LOG.info("terminating %s %s:%d with exit code %d", job_name, rtype, index, exit_code)
    payload = _http_get_json(url, timeout=timeout)
    if payload.get("exiting") != exit_code:
        raise TestFailure(f"unexpected /exit reply: {payload}")


def get_tfconfig(
    client: ClusterClient, namespace: str, job_name: str, rtype: str, index: int = 0
) -> dict:
    """GET /tfconfig from a replica — verifies the injected contract E2E."""
    ip, port = replica_address(client, namespace, job_name, rtype, index)
    return _http_get_json(f"http://{ip}:{port}/tfconfig")


# ---------------------------------------------------------------------------
# Event accounting (parse_events analog)
# ---------------------------------------------------------------------------

def count_creation_events(
    client: ClusterClient, namespace: str, job_name: str
) -> tuple[set[str], set[str]]:
    """(created pod names, created service names) from the event stream
    (test_runner.py:217-281 semantics: events are the audit trail). Creation
    events attach to the owning job with the created object's name in the
    message ("Created pod: {name}" — pod_control.py)."""
    from tf_operator_tpu.runtime import events as ev

    pods: set[str] = set()
    services: set[str] = set()
    for e in client.list(objects.EVENTS, namespace):
        if e.get("involvedObject", {}).get("name") != job_name:
            continue
        message = e.get("message", "")
        created = message.rsplit(": ", 1)[-1] if ": " in message else ""
        if e.get("reason") == ev.SUCCESSFUL_CREATE_POD and created:
            pods.add(created)
        elif e.get("reason") == ev.SUCCESSFUL_CREATE_SERVICE and created:
            services.add(created)
    return pods, services


# ---------------------------------------------------------------------------
# The driver
# ---------------------------------------------------------------------------

def default_job_spec(name: str, namespace: str, workers: int, ps: int,
                     restart_policy: str | None) -> dict:
    container = {
        "name": constants.DEFAULT_CONTAINER_NAME,
        "image": "tpu-operator/test-server",
        "command": [sys.executable, "-m", "tf_operator_tpu.harness.test_server"],
    }
    worker: dict = {"replicas": workers, "template": {"spec": {"containers": [container]}}}
    if restart_policy:
        worker["restartPolicy"] = restart_policy
    replica_specs = {"Worker": worker}
    if ps:
        replica_specs["PS"] = {
            "replicas": ps,
            "template": {"spec": {"containers": [dict(container)]}},
        }
    return {
        "apiVersion": constants.API_VERSION,
        "kind": constants.KIND,
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"replicaSpecs": replica_specs},
    }


def run_trial(
    client: ClusterClient,
    job_obj: dict,
    shutdown_policy: str,
    exit_code: int,
    timeout: float,
) -> None:
    """One deploy→assert→delete cycle (the body of run_test:373-585)."""
    cli = TPUJobClient(client)
    meta = job_obj["metadata"]
    ns, name = meta.get("namespace", "default"), meta["name"]

    cli.create(job_obj)
    try:
        cli.wait_for_running(ns, name, timeout=timeout)
        LOG.info("%s/%s running", ns, name)

        # The workers are live HTTP servers: check the injected contract.
        replica_types = list(job_obj["spec"]["replicaSpecs"])
        tfconfig = get_tfconfig(client, ns, name, replica_types[0], 0)
        if "cluster" not in tfconfig or "task" not in tfconfig:
            raise TestFailure(f"bad TF_CONFIG echoed by replica: {tfconfig}")

        if shutdown_policy != "none":
            rtype = {"chief": "Chief", "worker": "Worker", "ps": "PS"}[shutdown_policy]
            terminate_replica(client, ns, name, rtype, 0, exit_code)
            if exit_code == 0:
                # Exit-0 shutdown must end in Succeeded, but success needs
                # every worker (or the chief) to finish — the remaining
                # replicas would serve forever. Drain them too; ignore
                # replicas already torn down (e.g. chief-rule completion).
                for other_type, spec in job_obj["spec"]["replicaSpecs"].items():
                    for idx in range(int(spec.get("replicas", 1))):
                        if (other_type, idx) == (rtype, 0):
                            continue
                        try:
                            terminate_replica(
                                client, ns, name, other_type, idx, 0
                            )
                        except Exception as exc:  # noqa: BLE001
                            LOG.info(
                                "drain of %s-%d skipped: %s",
                                other_type, idx, exc,
                            )
        else:
            # No injected shutdown: ask every replica to exit 0 so the job
            # completes (the test server otherwise serves forever).
            for rtype, spec in job_obj["spec"]["replicaSpecs"].items():
                for idx in range(int(spec.get("replicas", 1))):
                    terminate_replica(client, ns, name, rtype, idx, 0)

        result = cli.wait_for_job(ns, name, timeout=timeout)
        conds = {
            c["type"]
            for c in result["status"]["conditions"]
            if c["status"] == "True"
        }
        expect_failed = shutdown_policy != "none" and exit_code not in (0,)
        if expect_failed and JobConditionType.FAILED not in conds:
            raise TestFailure(f"expected Failed, got {conds}")
        if not expect_failed:
            # Non-injected or exit-0 shutdown must succeed... unless other
            # replicas keep serving: chief exit-0 completes the job (chief
            # rule), worker exit-0 with remaining workers keeps Running —
            # handled by callers choosing sensible specs.
            if JobConditionType.SUCCEEDED not in conds:
                raise TestFailure(f"expected Succeeded, got {conds}")

        # Event accounting: every expected pod/service has a creation event.
        pods, services = count_creation_events(client, ns, name)
        expected = sum(
            int(s.get("replicas", 1)) for s in job_obj["spec"]["replicaSpecs"].values()
        )
        if len(pods) < expected:
            raise TestFailure(
                f"expected ≥{expected} pod creation events, saw {len(pods)}"
            )
        if len(services) < expected:
            raise TestFailure(
                f"expected ≥{expected} service creation events, saw {len(services)}"
            )
    finally:
        try:
            cli.delete(ns, name)
            cli.wait_for_delete(ns, name, timeout=timeout)
        except Exception:
            LOG.exception("cleanup failed for %s/%s", ns, name)

    # GC: no owned pods may survive deletion (test/e2e/main.go:244-252).
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not client.list(
            objects.PODS, ns, label_selector={constants.LABEL_JOB_NAME: name}
        ):
            return
        time.sleep(0.2)
    raise TestFailure(f"pods of {ns}/{name} not garbage-collected")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpu-test-runner", description=__doc__)
    p.add_argument("--master", default="http://127.0.0.1:8080")
    p.add_argument("--spec", default=None, help="TPUJob JSON file (default: builtin)")
    p.add_argument("--name", default="e2e-test-job")
    p.add_argument("--namespace", default="default")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--ps", type=int, default=0)
    p.add_argument("--restart-policy", default=None,
                   choices=[None, "Never", "OnFailure", "Always", "ExitCode"])
    p.add_argument("--shutdown-policy", default="none",
                   choices=["none", "chief", "worker", "ps"],
                   help="which replica to /exit (none = clean completion)")
    p.add_argument("--exit-code", type=int, default=0)
    p.add_argument("--trials", type=int, default=1,
                   help="repeat count (reference runs 2 trials)")
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--junit-path", default=None)
    args = p.parse_args(argv)

    logger.configure()
    from tf_operator_tpu.runtime.restclient import RestClusterClient

    client = RestClusterClient(args.master)
    if args.spec:
        with open(args.spec) as f:
            job_obj = json.load(f)
    else:
        job_obj = default_job_spec(
            args.name, args.namespace, args.workers, args.ps, args.restart_policy
        )

    cases: list[junit.TestCase] = []
    failed = 0
    for trial in range(args.trials):
        case = junit.TestCase(name=f"{args.name}-trial-{trial}")
        try:
            junit.wrap_test(
                lambda: run_trial(
                    client, json.loads(json.dumps(job_obj)),
                    args.shutdown_policy, args.exit_code, args.timeout,
                ),
                case,
            )
            LOG.info("trial %d passed (%.1fs)", trial, case.time)
        except Exception as e:
            failed += 1
            LOG.error("trial %d FAILED: %s", trial, e)
        cases.append(case)

    if args.junit_path:
        junit.write_junit_xml(cases, args.junit_path)
        LOG.info("junit written to %s", args.junit_path)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
