"""JUnit XML test reporting.

Parity: py/test_util.py:15-187 (TestCase/TestSuite, create_xml,
create_junit_xml_file, get_num_failures, wrap_test) — the artifact format CI
systems consume from E2E runs.
"""

from __future__ import annotations

import time
import traceback
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class TestCase:
    name: str = ""
    class_name: str = "e2e"
    time: float = 0.0
    failure: str | None = None

    @property
    def passed(self) -> bool:
        return self.failure is None


@dataclass
class TestSuite:
    name: str = "tpujob-e2e"
    cases: list[TestCase] = field(default_factory=list)


def wrap_test(test_func: Callable[[], None], test_case: TestCase) -> None:
    """Run test_func, recording wall time and any exception into test_case,
    re-raising after recording (test_util.py:73-96 semantics)."""
    start = time.monotonic()
    try:
        test_func()
    except Exception:
        test_case.failure = traceback.format_exc()
        raise
    finally:
        test_case.time = time.monotonic() - start


def create_xml(cases: list[TestCase], suite_name: str = "tpujob-e2e") -> str:
    failures = sum(1 for c in cases if not c.passed)
    root = ET.Element(
        "testsuite",
        name=suite_name,
        tests=str(len(cases)),
        failures=str(failures),
        time=f"{sum(c.time for c in cases):.3f}",
    )
    for c in cases:
        el = ET.SubElement(
            root,
            "testcase",
            classname=c.class_name,
            name=c.name,
            time=f"{c.time:.3f}",
        )
        if c.failure is not None:
            f = ET.SubElement(el, "failure", message="test failed")
            f.text = c.failure
    return ET.tostring(root, encoding="unicode")


def write_junit_xml(cases: list[TestCase], output_path: str,
                    suite_name: str = "tpujob-e2e") -> None:
    with open(output_path, "w") as f:
        f.write(create_xml(cases, suite_name))


def get_num_failures(xml_string: str) -> int:
    return int(ET.fromstring(xml_string).attrib.get("failures", "0"))
