"""E2E harness: fake-workload server, test driver, junit reporting (§2.7)."""
