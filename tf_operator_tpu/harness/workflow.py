"""CI workflow DAG runner — the Argo-workflow analog, runnable anywhere.

Parity: test/workflows/components/workflows.libsonnet:190-250 (the reference
E2E DAG: checkout → build + py-test in parallel → setup cluster → run test
suites in parallel → teardown, with per-step artifacts/logs and a junit
summary consumed by Prow). The reference needs an Argo controller on a GKE
cluster to execute that DAG; here the DAG executes locally with threads —
same topology semantics (steps run as soon as their deps pass; a failure
skips all transitive dependents; independent branches run concurrently),
writing the same artifact contract (started.json/finished.json, per-step
logs, junit XML).

    wf = Workflow("e2e", [Step("build", [sys.executable, "-m", ...]),
                          Step("test", ..., deps=("build",))])
    ok = wf.run(artifacts_dir)

Steps are either subprocess commands (list[str]) or Python callables taking
a context dict ({"artifacts_dir", "env", "outputs"}); callables can publish
outputs (e.g. the deployed master URL) for downstream steps to read.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable

from tf_operator_tpu.harness import junit, prow
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="workflow")

PASSED, FAILED, SKIPPED = "passed", "failed", "skipped"


@dataclass
class Step:
    name: str
    action: list[str] | Callable[[dict[str, Any]], None]
    deps: tuple[str, ...] = ()
    timeout: float = 600.0
    env: dict[str, str] = field(default_factory=dict)
    # Exit-handler semantics (Argo onExit analog): run once all deps have
    # COMPLETED regardless of their status — for teardown steps that must
    # release resources even when the steps before them failed.
    always: bool = False


@dataclass
class StepResult:
    name: str
    status: str
    duration: float = 0.0
    message: str = ""


class Workflow:
    def __init__(self, name: str, steps: list[Step]) -> None:
        self.name = name
        self.steps = {s.name: s for s in steps}
        if len(self.steps) != len(steps):
            raise ValueError("duplicate step names")
        for s in steps:
            for d in s.deps:
                if d not in self.steps:
                    raise ValueError(f"step {s.name}: unknown dep {d}")
        self._check_acyclic()

    def _check_acyclic(self) -> None:
        seen: dict[str, int] = {}  # 1=visiting, 2=done

        def visit(n: str, chain: tuple[str, ...]) -> None:
            state = seen.get(n)
            if state == 2:
                return
            if state == 1:
                raise ValueError(f"dependency cycle: {' -> '.join(chain + (n,))}")
            seen[n] = 1
            for d in self.steps[n].deps:
                visit(d, chain + (n,))
            seen[n] = 2

        for n in self.steps:
            visit(n, ())

    # ------------------------------------------------------------------ run

    def run(self, artifacts_dir: str,
            env: dict[str, str] | None = None) -> bool:
        """Execute the DAG; returns True when every step passed."""
        os.makedirs(os.path.join(artifacts_dir, "logs"), exist_ok=True)
        prow.create_started(artifacts_dir)
        ctx: dict[str, Any] = {
            "artifacts_dir": artifacts_dir,
            "env": dict(env or {}),
            "outputs": {},  # step name -> published value
        }

        results: dict[str, StepResult] = {}
        running: set[str] = set()
        cond = threading.Condition()

        def runnable(name: str) -> bool:
            step = self.steps[name]
            if step.always:
                return all(d in results for d in step.deps)
            return all(
                d in results and results[d].status == PASSED
                for d in step.deps
            )

        def blocked_forever(name: str) -> bool:
            if self.steps[name].always:
                return False
            return any(
                d in results and results[d].status != PASSED
                for d in self.steps[name].deps
            )

        def execute(step: Step) -> None:
            t0 = time.monotonic()
            res = StepResult(step.name, PASSED)
            log_path = os.path.join(artifacts_dir, "logs", f"{step.name}.log")
            try:
                if callable(step.action):
                    # Enforce the timeout on callables too (a hung deploy
                    # must fail the step, not wedge the whole workflow).
                    # Python threads can't be killed: on timeout the step
                    # thread leaks until process exit, but the DAG proceeds.
                    err: list[BaseException] = []

                    def _call() -> None:
                        try:
                            step.action(ctx)
                        except BaseException as e:  # noqa: BLE001
                            err.append(e)

                    t = threading.Thread(
                        target=_call, name=f"wf-{step.name}-call", daemon=True
                    )
                    t.start()
                    t.join(step.timeout)
                    if t.is_alive():
                        raise TimeoutError(
                            f"step exceeded timeout ({step.timeout}s)"
                        )
                    if err:
                        raise err[0]
                else:
                    step_env = dict(os.environ)
                    step_env.update(ctx["env"])
                    step_env.update(step.env)
                    with open(log_path, "wb") as log_f:
                        proc = subprocess.run(
                            step.action, env=step_env, stdout=log_f,
                            stderr=subprocess.STDOUT, timeout=step.timeout,
                        )
                    if proc.returncode != 0:
                        res.status = FAILED
                        res.message = (
                            f"exit code {proc.returncode}; log: {log_path}"
                        )
            except Exception as exc:  # noqa: BLE001 — step isolation
                res.status = FAILED
                res.message = f"{type(exc).__name__}: {exc}"
                with open(log_path, "ab") as log_f:
                    log_f.write(traceback.format_exc().encode())
            res.duration = time.monotonic() - t0
            LOG.info("step %s: %s (%.1fs) %s", step.name, res.status,
                     res.duration, res.message)
            with cond:
                results[step.name] = res
                running.discard(step.name)
                cond.notify_all()

        with cond:
            while len(results) < len(self.steps):
                progressed = False
                for name, step in self.steps.items():
                    if name in results or name in running:
                        continue
                    if blocked_forever(name):
                        results[name] = StepResult(
                            name, SKIPPED, message="dependency failed"
                        )
                        progressed = True
                    elif runnable(name):
                        running.add(name)
                        threading.Thread(
                            target=execute, args=(step,),
                            name=f"wf-{name}", daemon=True,
                        ).start()
                        progressed = True
                if len(results) == len(self.steps):
                    break
                if not progressed and not running:
                    raise RuntimeError("workflow wedged (scheduler bug)")
                if not progressed:
                    cond.wait()

        ordered = [results[n] for n in self.steps]
        success = all(r.status == PASSED for r in ordered)
        cases = [
            junit.TestCase(
                name=r.name, class_name=self.name, time=r.duration,
                failure=None if r.status == PASSED else f"{r.status}: {r.message}",
            )
            for r in ordered
        ]
        junit.write_junit_xml(
            cases, os.path.join(artifacts_dir, f"junit_{self.name}.xml")
        )
        prow.create_finished(
            artifacts_dir, success,
            {r.name: r.status for r in ordered},
        )
        self.results = results
        return success


# ---------------------------------------------------------------------------
# The default CI workflow — the reference E2E DAG rebuilt for this framework
# (workflows.libsonnet topology: build + unit in parallel → deploy operator →
# e2e suite → teardown-always).
# ---------------------------------------------------------------------------


def default_e2e_workflow(
    *,
    # Default: the documented fast tier (README "Fast vs full tier") — every
    # suite except the slow-marked training/scale E2Es. Callers (and the
    # nested workflow run inside test_ci_tooling) override with a narrower
    # selection via --unit-tests.
    unit_tests: tuple[str, ...] = ("tests", "-m", "not slow"),
    e2e_workers: int = 2,
    e2e_trials: int = 1,
) -> Workflow:
    import sys

    from tf_operator_tpu.harness.deploy import REPO_ROOT, OperatorDeployment

    def build(ctx: dict[str, Any]) -> None:
        from tf_operator_tpu.release.build import build_release

        manifest = build_release(
            REPO_ROOT, os.path.join(ctx["artifacts_dir"], "dist")
        )
        ctx["outputs"]["release"] = manifest

    def deploy(ctx: dict[str, Any]) -> None:
        dep = OperatorDeployment(
            log_path=os.path.join(ctx["artifacts_dir"], "logs", "operator.log")
        )
        dep.start()
        ctx["outputs"]["master"] = dep.master
        ctx["outputs"]["deployment"] = dep

    def e2e(ctx: dict[str, Any]) -> None:
        from tf_operator_tpu.harness import test_runner

        rc = test_runner.main([
            "--master", ctx["outputs"]["master"],
            "--name", "wf-e2e",
            "--workers", str(e2e_workers),
            "--trials", str(e2e_trials),
            # Per-phase job wait: generous for contended single-core CI
            # hosts (process spawn + reconcile latency scales with load).
            "--timeout", "240",
            "--junit-path",
            os.path.join(ctx["artifacts_dir"], "junit_e2e_suite.xml"),
        ])
        if rc != 0:
            raise RuntimeError(f"e2e suite failed (rc={rc})")

    def teardown(ctx: dict[str, Any]) -> None:
        dep = ctx["outputs"].get("deployment")
        if dep is not None:
            dep.stop()

    def realcluster(ctx: dict[str, Any]) -> None:
        """Optional real-apiserver conformance stage (reference parity:
        prow_config.yaml:5-17 stands up a live GKE cluster for every CI
        run). Here no cluster is reachable in CI, so the stage runs the
        real-apiserver smoke ONLY when TPUFLOW_E2E_KUBECONFIG points at a
        cluster (kind/minikube/GKE — see docs/developer_guide.md "Real
        cluster profile"), and otherwise records an explicit skip. It must
        be skipped-not-broken: the day a cluster exists, no new code is
        needed."""
        kubeconfig = os.environ.get("TPUFLOW_E2E_KUBECONFIG", "")
        if not kubeconfig:
            ctx["outputs"]["realcluster"] = (
                "skipped: TPUFLOW_E2E_KUBECONFIG not set"
            )
            return
        step_env = dict(os.environ)
        step_env["PYTHONPATH"] = (
            REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
        )
        log_path = os.path.join(
            ctx["artifacts_dir"], "logs", "realcluster_pytest.log"
        )
        with open(log_path, "wb") as log_f:
            proc = subprocess.run(
                [sys.executable, "-m", "pytest", "-q",
                 "tests/test_kubeclient.py::test_real_apiserver_smoke"],
                env=step_env, stdout=log_f, stderr=subprocess.STDOUT,
                timeout=540.0, cwd=REPO_ROOT,
            )
        if proc.returncode != 0:
            raise RuntimeError(
                f"real-apiserver smoke failed (rc={proc.returncode}); "
                f"log: {log_path}"
            )
        ctx["outputs"]["realcluster"] = f"ran against {kubeconfig}"

    env = {"PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")}
    return Workflow(
        "tpu-operator-e2e",
        [
            Step("build", build),
            Step("unit", [
                sys.executable, "-m", "pytest", "-q", *unit_tests,
            ], env=env, timeout=900.0),
            Step("deploy", deploy, deps=("build",)),
            Step("e2e", e2e, deps=("deploy",), timeout=900.0),
            Step("realcluster", realcluster, deps=("e2e",), timeout=600.0),
            Step("teardown", teardown, deps=("deploy", "e2e"), always=True),
        ],
    )


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--artifacts", default="artifacts")
    p.add_argument("--unit-tests", nargs="*", default=None)
    args = p.parse_args(argv)
    kwargs: dict[str, Any] = {}
    if args.unit_tests is not None:
        kwargs["unit_tests"] = tuple(args.unit_tests)
    wf = default_e2e_workflow(**kwargs)
    ok = wf.run(args.artifacts)
    print(f"workflow {wf.name}: {'SUCCESS' if ok else 'FAILURE'}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
