"""Controllable fake workload — the E2E test double for a training process.

Parity: test/test-server/test_app.py:25-41 in the reference — a tiny HTTP
app run *as* the replica container so cluster E2E can exercise lifecycle
semantics (restart policies, chief-vs-worker termination, GC)
deterministically without any ML framework in the loop:

- GET /tfconfig          → echoes the injected TF_CONFIG (JSON)
- GET /topology          → echoes the injected TPU mesh env (the TPU analog
                           SURVEY.md §4 calls for)
- GET /exit?exitCode=n   → replies, then kills this replica with exit code n
- GET /healthz           → liveness
- GET /                  → identity summary

Run: python -m tf_operator_tpu.harness.test_server  (port from PORT env,
default 2222).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from tf_operator_tpu.api import constants

TPU_ENV_KEYS = (
    constants.ENV_TPU_WORKER_HOSTNAMES,
    constants.ENV_TPU_WORKER_ID,
    constants.ENV_TPU_ACCELERATOR_TYPE,
    constants.ENV_TPU_TOPOLOGY,
    constants.ENV_COORDINATOR_ADDRESS,
    constants.ENV_NUM_PROCESSES,
    "MEGASCALE_NUM_SLICES",
    "MEGASCALE_SLICE_ID",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


class _Handler(BaseHTTPRequestHandler):
    def _reply(self, payload: dict, code: int = 200) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib API)
        url = urlparse(self.path)
        if url.path == "/tfconfig":
            raw = os.environ.get(constants.ENV_TF_CONFIG, "")
            try:
                self._reply(json.loads(raw) if raw else {})
            except ValueError:
                self._reply({"raw": raw})
        elif url.path == "/topology":
            self._reply({k: os.environ[k] for k in TPU_ENV_KEYS if k in os.environ})
        elif url.path == "/exit":
            try:
                code = int(parse_qs(url.query).get("exitCode", ["0"])[0])
            except ValueError:
                self._reply({"error": "exitCode must be an integer"}, code=400)
                return
            self._reply({"exiting": code})
            # Reply first, then die — the harness needs the ACK.
            threading.Timer(0.05, lambda: os._exit(code)).start()
        elif url.path == "/healthz":
            self._reply({"ok": True})
        else:
            self._reply(
                {
                    "server": "tpu-operator-test-server",
                    "task_index": os.environ.get(constants.ENV_TPU_WORKER_ID),
                }
            )

    def log_message(self, fmt: str, *args) -> None:  # quiet
        pass


def main() -> None:
    port = int(os.environ.get("PORT", constants.DEFAULT_PORT))
    server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    server.serve_forever()


if __name__ == "__main__":
    main()
