"""Whole-program class/lock model shared by the concurrency passes.

This module turns the parsed tree set into:

- per-class **lock attributes** (``self._lock = threading.Lock()`` and
  friends, including ``param or threading.Lock()`` and lock-annotated
  constructor params) plus module-level and function-local locks;
- per-class **attribute types** (``self._x = ClassName(...)``,
  annotated params/attrs) resolved across modules through imports, so
  the lock-order pass can follow ``self._membership.probe()`` into
  ``FleetMembership``;
- per-method **facts**: every lock acquisition, every ``self.X``
  access, and every call — each annotated with the ordered list of
  locks held at that point (a linear symbolic walk over the statement
  tree: ``with`` bodies, bare ``acquire()``/``release()`` spans, the
  ``while not lock.acquire(timeout=..)`` idiom, try/finally).

Lock node ids are instance-agnostic (``<module>.<Class>.<attr>``), the
classic abstraction for lock-order analysis. ``creation sites`` —
(file, line) of each ``threading.Lock()``-family call — are exported so
the runtime witness (runtime/lockwitness.py) can map live lock objects
back onto static nodes by the site that allocated them.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tf_operator_tpu.harness.lint.base import SourceFile, dotted_name

LOCK_FACTORIES = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
}
# Names that annotate a lock-typed constructor param.
_LOCK_ANNOTATIONS = {"Lock", "RLock", "Condition"}


@dataclass(frozen=True)
class LockRef:
    """A lock as referenced from inside one method."""

    scope: str   # "self" | "module" | "local" | "other"
    name: str    # attribute / global / local name; for "other" the
    #              fully-qualified "<module>.<Class>.<attr>" node id
    kind: str | None = None   # pre-resolved kind for "other" refs


@dataclass
class LockInfo:
    kind: str                    # lock | rlock | condition
    site_line: int | None        # line of the threading.X() call, if created
    alias_params: tuple[str, ...] = ()   # ctor params this attr may alias
    # qual of the DEFINING class when the attr is inherited — lock nodes
    # are named after the class that creates the lock (Counter/Gauge/
    # Histogram all share _Family._lock)
    owner_qual: str | None = None


@dataclass
class AccessFact:
    attr: str
    is_write: bool
    line: int
    held: tuple[LockRef, ...]


@dataclass
class CallFact:
    dotted: str | None           # "self._engine.step", "time.sleep", ...
    node: ast.Call
    line: int
    held: tuple[LockRef, ...]
    # class name (as written) of the receiver when it is a param/local
    # with a known type: `sched.fence_and_harvest()` with
    # `sched: ContinuousScheduler` resolves cross-class
    recv_type: str | None = None


@dataclass
class AcquireFact:
    ref: LockRef
    line: int
    held: tuple[LockRef, ...]    # held BEFORE this acquisition


@dataclass
class MethodFacts:
    name: str                    # may be "meth.<locals>.fn" for nested defs
    entry_public: bool           # analyzed as externally callable
    acquires: list[AcquireFact] = field(default_factory=list)
    accesses: list[AccessFact] = field(default_factory=list)
    calls: list[CallFact] = field(default_factory=list)


@dataclass
class ClassModel:
    module: str
    rel: str
    name: str
    node: ast.ClassDef
    bases: tuple[str, ...]
    methods: dict[str, ast.FunctionDef] = field(default_factory=dict)
    lock_attrs: dict[str, LockInfo] = field(default_factory=dict)
    attr_types: dict[str, str] = field(default_factory=dict)
    event_attrs: set[str] = field(default_factory=set)
    thread_attrs: set[str] = field(default_factory=set)
    facts: dict[str, MethodFacts] = field(default_factory=dict)
    # module-level lock names visible from this class's methods
    module_locks: dict[str, LockInfo] = field(default_factory=dict)
    is_module_scope: bool = False  # synthetic holder of top-level functions
    # memo for @contextmanager lock extraction (`with self._device():`)
    ctx_cache: dict[str, tuple["LockRef", ...]] = field(default_factory=dict)
    # creation line -> node id for function-local locks (the witness
    # maps live locks by creation site; locals must be nameable too)
    local_lock_sites: dict[int, str] = field(default_factory=dict)

    @property
    def qual(self) -> str:
        return f"{self.module}.{self.name}"

    def lock_node(self, attr: str) -> str:
        return f"{self.qual}.{attr}"


@dataclass
class ModuleModel:
    sf: SourceFile
    classes: dict[str, ClassModel] = field(default_factory=dict)
    module_locks: dict[str, LockInfo] = field(default_factory=dict)
    imports: dict[str, str] = field(default_factory=dict)  # local -> dotted
    # module-level instances: NAME = ClassName(...) -> class name as written
    global_types: dict[str, str] = field(default_factory=dict)


@dataclass
class Project:
    modules: dict[str, ModuleModel] = field(default_factory=dict)  # dotted
    classes: dict[str, ClassModel] = field(default_factory=dict)   # qual

    def resolve_class(self, mod: ModuleModel, name: str) -> ClassModel | None:
        """Resolve a (possibly dotted) name used in ``mod`` to a class."""
        if name in mod.classes:
            return mod.classes[name]
        if "." in name:
            head, _, rest = name.partition(".")
            target = mod.imports.get(head)
            if target is not None:
                return self.classes.get(f"{target}.{rest}")
            return self.classes.get(name)
        target = mod.imports.get(name)
        if target is not None:
            return self.classes.get(target)
        return None

    def resolve_type(self, mod: ModuleModel, name: str) -> ClassModel | None:
        """Like resolve_class, but a name that denotes a module-level
        INSTANCE (``NULL_INJECTOR``, ``SERVE_TRACER``) resolves to the
        instance's class — local or imported."""
        got = self.resolve_class(mod, name)
        if got is not None:
            return got
        tname = mod.global_types.get(name)
        if tname is not None:
            return self.resolve_class(mod, tname)
        target = mod.imports.get(name)
        if target is not None:
            owner_mod, _, owner_name = target.rpartition(".")
            owner_mm = self.modules.get(owner_mod)
            if owner_mm is not None:
                tname = owner_mm.global_types.get(owner_name)
                if tname is not None:
                    return self.resolve_class(owner_mm, tname)
        return None


def _lock_call_kind(node: ast.expr) -> tuple[str, int] | None:
    """threading.Lock() / Lock() style call -> (kind, line)."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    kind = LOCK_FACTORIES.get(name or "")
    if kind is None:
        return None
    return kind, node.lineno


def _find_lock_call(expr: ast.expr) -> tuple[str, int] | None:
    """Find a lock-factory call anywhere inside expr (covers the
    ``param or threading.Lock()`` default idiom)."""
    for sub in ast.walk(expr):
        got = _lock_call_kind(sub)
        if got is not None:
            return got
    return None


def _annotation_lock_kind(ann: ast.expr | None) -> str | None:
    if ann is None:
        return None
    for sub in ast.walk(ann):
        name = None
        if isinstance(sub, ast.Attribute) and isinstance(sub.value, ast.Name) \
                and sub.value.id == "threading":
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name in _LOCK_ANNOTATIONS:
            return LOCK_FACTORIES[name]
    return None


def _annotation_type_name(ann: ast.expr | None) -> str | None:
    """'ClassName' out of ``ClassName``/``ClassName | None``/``Optional[..]``
    annotations (string annotations included)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        for side in (ann.left, ann.right):
            got = _annotation_type_name(side)
            if got is not None:
                return got
        return None
    if isinstance(ann, ast.Subscript):
        base = dotted_name(ann.value)
        if base in ("Optional", "typing.Optional"):
            return _annotation_type_name(
                ann.slice if not isinstance(ann.slice, ast.Tuple) else None
            )
        return None
    if isinstance(ann, ast.Constant) and ann.value is None:
        return None
    name = dotted_name(ann)
    if name in (None, "None", "Any", "typing.Any", "object"):
        return None
    return name


# ---------------------------------------------------------------------------
# Model construction
# ---------------------------------------------------------------------------


def build_project(files: list[SourceFile]) -> Project:
    proj = Project()
    for sf in files:
        if sf.tree is None:
            continue
        mm = ModuleModel(sf=sf)
        _collect_imports(sf.tree, mm)
        _collect_module_locks(sf.tree, mm)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                cm = _build_class(sf, node)
                mm.classes[cm.name] = cm
                proj.classes.setdefault(cm.qual, cm)
        # synthetic scope for module-level functions (they use module
        # locks: native._LOCK, serve.httpapi._ttft_lock, ...)
        modscope = ClassModel(
            module=sf.module, rel=sf.rel, name="<module>",
            node=ast.ClassDef(
                name="<module>", bases=[], keywords=[], body=[],
                decorator_list=[],
            ),
            bases=(), is_module_scope=True,
        )
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                modscope.methods[node.name] = node  # type: ignore[assignment]
        mm.classes["<module>"] = modscope
        proj.modules[sf.module] = mm
    _resolve_inheritance(proj)
    for mm in proj.modules.values():
        # The concurrency passes (facts-driven: lock-order, guarded-attr,
        # blocking-under-lock) cover SHIPPED code; test modules are
        # covered at runtime by the lock witness instead (the chaos
        # suites assert observed edges ⊆ this graph), and skipping their
        # method walks roughly halves the gate's cost. The AST-driven
        # passes (metrics-registry, typed-error) still scan tests.
        if mm.sf.rel.startswith("tests/") \
                and "lint_fixtures" not in mm.sf.rel:
            continue
        for cm in mm.classes.values():
            cm.module_locks = mm.module_locks
            for name, fn in list(cm.methods.items()):
                _walk_method(cm, name, fn, proj, mm)
    return proj


def _resolve_inheritance(proj: Project) -> None:
    """Merge base-class lock/event/thread/type attrs into subclasses so
    ``with self._lock`` in a subclass method resolves to the lock the
    base created (named after the defining class)."""
    done: set[str] = set()

    def resolve(cm: ClassModel, depth: int = 0) -> None:
        if cm.qual in done or depth > 8:
            return
        done.add(cm.qual)
        mm = proj.modules.get(cm.module)
        if mm is None:
            return
        for bname in cm.bases:
            bcm = proj.resolve_class(mm, bname)
            if bcm is None or bcm.qual == cm.qual:
                continue
            resolve(bcm, depth + 1)
            for attr, info in bcm.lock_attrs.items():
                if attr not in cm.lock_attrs:
                    cm.lock_attrs[attr] = LockInfo(
                        info.kind, info.site_line, info.alias_params,
                        owner_qual=info.owner_qual or bcm.qual,
                    )
            for attr, t in bcm.attr_types.items():
                cm.attr_types.setdefault(attr, t)
            cm.event_attrs |= bcm.event_attrs
            cm.thread_attrs |= bcm.thread_attrs

    for cm in list(proj.classes.values()):
        resolve(cm)


def method_owner(proj: Project, cm: ClassModel, meth: str,
                 depth: int = 0) -> ClassModel | None:
    """The class (``cm`` or a base) whose ``facts`` define ``meth``."""
    if meth in cm.facts:
        return cm
    if depth > 8:
        return None
    mm = proj.modules.get(cm.module)
    if mm is None:
        return None
    for bname in cm.bases:
        bcm = proj.resolve_class(mm, bname)
        if bcm is not None and bcm.qual != cm.qual:
            got = method_owner(proj, bcm, meth, depth + 1)
            if got is not None:
                return got
    return None


def _collect_imports(tree: ast.Module, mm: ModuleModel) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                mm.imports[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name != "*":
                    mm.imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"


# REGISTRY.counter(...) / .gauge / .histogram return these family classes
# (runtime/metrics.py) — special-cased so "metric mutation under a lock"
# edges resolve to the family's internal lock.
_REGISTRY_FACTORY_TYPES = {
    "counter": "tf_operator_tpu.runtime.metrics.Counter",
    "gauge": "tf_operator_tpu.runtime.metrics.Gauge",
    "histogram": "tf_operator_tpu.runtime.metrics.Histogram",
}


def _value_class_name(value: ast.expr | None) -> str | None:
    """Class name (as written) a value expression instantiates, covering
    the ``x or ClassName()`` default idiom and registry factories."""
    if value is None:
        return None
    for sub in ast.walk(value):
        if not isinstance(sub, ast.Call):
            continue
        callee = dotted_name(sub.func)
        if callee is None or callee.startswith("self."):
            continue
        parts = callee.split(".")
        if len(parts) >= 2 and parts[-2] == "REGISTRY" \
                and parts[-1] in _REGISTRY_FACTORY_TYPES:
            return _REGISTRY_FACTORY_TYPES[parts[-1]]
        if parts[-1][:1].isupper():
            return callee
    return None


def _collect_module_locks(tree: ast.Module, mm: ModuleModel) -> None:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            got = _find_lock_call(node.value)
            if got is not None:
                kind, line = got
                mm.module_locks[node.targets[0].id] = LockInfo(kind, line)
                continue
            tname = _value_class_name(node.value)
            if tname is not None:
                mm.global_types[node.targets[0].id] = tname


def _build_class(sf: SourceFile, node: ast.ClassDef) -> ClassModel:
    cm = ClassModel(
        module=sf.module, rel=sf.rel, name=node.name, node=node,
        bases=tuple(
            d for d in (dotted_name(b) for b in node.bases) if d
        ),
    )
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cm.methods[item.name] = item  # type: ignore[assignment]
        elif isinstance(item, ast.AnnAssign) \
                and isinstance(item.target, ast.Name):
            # class-body annotation: `server: KubeApiStub` on a handler
            # types an attribute the framework injects at runtime
            tname = _annotation_type_name(item.annotation)
            if tname is not None:
                cm.attr_types.setdefault(item.target.id, tname)
    for meth in cm.methods.values():
        param_ann = {
            a.arg: a.annotation
            for a in list(meth.args.posonlyargs) + list(meth.args.args)
            + list(meth.args.kwonlyargs)
        }
        for st in ast.walk(meth):
            attr, value, ann = None, None, None
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                tgt = st.targets[0]
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    attr, value = tgt.attr, st.value
            elif isinstance(st, ast.AnnAssign):
                tgt = st.target
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    attr, value, ann = tgt.attr, st.value, st.annotation
            if attr is None:
                continue
            _classify_attr(cm, attr, value, ann, param_ann)
    return cm


def _classify_attr(cm: ClassModel, attr: str, value: ast.expr | None,
                   ann: ast.expr | None,
                   param_ann: dict[str, ast.expr | None]) -> None:
    lock = _find_lock_call(value) if value is not None else None
    aliases: list[str] = []
    if value is not None:
        for sub in ast.walk(value):
            if isinstance(sub, ast.Name) and sub.id in param_ann:
                aliases.append(sub.id)
    if lock is not None:
        kind, line = lock
        cm.lock_attrs.setdefault(
            attr, LockInfo(kind, line, tuple(aliases))
        )
        return
    # lock-annotated attr or lock-annotated ctor param assigned through
    ann_kind = _annotation_lock_kind(ann)
    if ann_kind is None and isinstance(value, ast.Name) \
            and value.id in param_ann:
        ann_kind = _annotation_lock_kind(param_ann[value.id])
    if ann_kind is not None:
        cm.lock_attrs.setdefault(
            attr, LockInfo(ann_kind, None, tuple(aliases))
        )
        return
    if value is not None:
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                callee = dotted_name(sub.func)
                if callee == "threading.Event":
                    cm.event_attrs.add(attr)
                    return
                if callee == "threading.Thread":
                    cm.thread_attrs.add(attr)
                    return
        tname = _value_class_name(value)
        if tname is not None:
            cm.attr_types.setdefault(attr, tname)
            return
        # self._sched_cls = ContinuousScheduler (a class stored to call
        # later) — record so `self.X = self._sched_cls(...)` resolves
        if isinstance(value, ast.Name) and value.id[:1].isupper():
            cm.attr_types.setdefault(attr, value.id)
            return
        # `self.faults = faults or NULL_INJECTOR`: the default names a
        # module-level instance — its type resolves at pass time via
        # global_types (see Project.resolve_type)
        if isinstance(value, ast.BoolOp) and isinstance(value.op, ast.Or):
            last = value.values[-1]
            if isinstance(last, ast.Name) and last.id[:1].isupper():
                cm.attr_types.setdefault(attr, last.id)
                return
        # self._sched = self._sched_cls(...): type of the called attr
        if isinstance(value, ast.Call):
            callee = dotted_name(value.func)
            if callee is not None and callee.startswith("self.") \
                    and callee.count(".") == 1:
                src = callee.split(".")[1]
                if src in cm.attr_types:
                    cm.attr_types.setdefault(attr, cm.attr_types[src])
                    return
    # typed via annotation, or via an annotated ctor param
    tname = _annotation_type_name(ann)
    if tname is None and isinstance(value, ast.Name) \
            and value.id in param_ann:
        tname = _annotation_type_name(param_ann[value.id])
    if tname is not None and tname not in ("threading.Lock",):
        cm.attr_types.setdefault(attr, tname)


# ---------------------------------------------------------------------------
# The held-region walker
# ---------------------------------------------------------------------------


class _Walk:
    def __init__(self, cm: ClassModel, facts: MethodFacts,
                 param_types: dict[str, str] | None = None,
                 proj: "Project | None" = None,
                 mm: "ModuleModel | None" = None) -> None:
        self.cm = cm
        self.facts = facts
        self.proj = proj
        self.mm = mm
        self.held: list[LockRef] = []
        self.local_locks: dict[str, LockInfo] = {}
        self.local_types: dict[str, str] = dict(param_types or {})
        self.nested: list[tuple[str, ast.FunctionDef]] = []

    # -- lock reference resolution --------------------------------------

    def lock_ref(self, expr: ast.expr) -> LockRef | None:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" \
                and expr.attr in self.cm.lock_attrs:
            return LockRef("self", expr.attr)
        if isinstance(expr, ast.Name):
            if expr.id in self.local_locks:
                return LockRef("local", expr.id)
            if expr.id in self.cm.module_locks:
                return LockRef("module", expr.id)
            return None
        return None

    def _value_type(self, value: ast.expr) -> str | None:
        """Type of a local assignment's value: ``ClassName(...)``,
        ``self._attr`` / ``self._cls_attr(...)`` with known attr types."""
        got = _value_class_name(value)
        if got is not None:
            return got
        if isinstance(value, ast.Call):
            callee = dotted_name(value.func)
            if callee is not None and callee.startswith("self.") \
                    and callee.count(".") == 1:
                return self.cm.attr_types.get(callee.split(".")[1])
        if isinstance(value, ast.Attribute) \
                and isinstance(value.value, ast.Name) \
                and value.value.id == "self":
            return self.cm.attr_types.get(value.attr)
        return None

    def _ctxmgr_locks(self, expr: ast.expr) -> tuple[LockRef, ...]:
        if not isinstance(expr, ast.Call):
            return ()
        callee = dotted_name(expr.func)
        if callee is None or not callee.startswith("self."):
            return ()
        if callee.count(".") == 2:
            return self._ctxmgr_other_locks(callee)
        if callee.count(".") != 1:
            return ()
        meth = callee.split(".")[1]
        fn = self.cm.methods.get(meth)
        if fn is None:
            return ()
        key = meth
        cached = self.cm.ctx_cache.get(key)
        if cached is None:
            refs: list[LockRef] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "acquire":
                    tgt = node.func.value
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self" \
                            and tgt.attr in self.cm.lock_attrs:
                        refs.append(LockRef("self", tgt.attr))
            cached = tuple(dict.fromkeys(refs))
            self.cm.ctx_cache[key] = cached
        return cached

    def _ctxmgr_other_locks(self, callee: str) -> tuple[LockRef, ...]:
        """``with self.server.mutation_lock(kind):`` — a method on a
        TYPED attribute that hands back one of its class's locks (the
        kubestub/apiserver per-kind mutation serialization idiom). The
        target method is scanned for ``return self.<lockattr>``; a
        conditional nullcontext branch over-approximates to "held",
        which is sound for ordering (extra static edges, never missing
        ones)."""
        if self.proj is None or self.mm is None:
            return ()
        _, attr, meth = callee.split(".")
        tname = self.cm.attr_types.get(attr)
        if tname is None:
            return ()
        tcm = self.proj.resolve_type(self.mm, tname)
        if tcm is None:
            return ()
        key = f"{attr}.{meth}"
        cached = self.cm.ctx_cache.get(key)
        if cached is None:
            refs: list[LockRef] = []
            fn = tcm.methods.get(meth)
            if fn is not None:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Return) \
                            and isinstance(node.value, ast.Attribute) \
                            and isinstance(node.value.value, ast.Name) \
                            and node.value.value.id == "self" \
                            and node.value.attr in tcm.lock_attrs:
                        info = tcm.lock_attrs[node.value.attr]
                        owner = info.owner_qual or tcm.qual
                        refs.append(LockRef(
                            "other", f"{owner}.{node.value.attr}",
                            kind=info.kind,
                        ))
            cached = tuple(dict.fromkeys(refs))
            self.cm.ctx_cache[key] = cached
        return cached

    def push(self, ref: LockRef, line: int) -> None:
        self.facts.acquires.append(
            AcquireFact(ref, line, tuple(self.held))
        )
        self.held.append(ref)

    def pop(self, ref: LockRef) -> None:
        if ref in self.held:
            # remove the LAST occurrence (re-entrant with-nesting)
            for i in range(len(self.held) - 1, -1, -1):
                if self.held[i] == ref:
                    del self.held[i]
                    break

    # -- statements ------------------------------------------------------

    def body(self, stmts: list[ast.stmt]) -> None:
        for st in stmts:
            self.stmt(st)

    def stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.With, ast.AsyncWith)):
            pushed: list[LockRef] = []
            for item in st.items:
                ref = self.lock_ref(item.context_expr)
                if ref is not None:
                    self.push(ref, item.context_expr.lineno)
                    pushed.append(ref)
                    continue
                # `with self._device():` — a same-class @contextmanager
                # that acquires a lock for its body (the serve
                # scheduler's heartbeating device mutex). Scan the call
                # BEFORE pushing: entering the manager happens unheld.
                self.expr(item.context_expr)
                for cref in self._ctxmgr_locks(item.context_expr):
                    self.push(cref, item.context_expr.lineno)
                    pushed.append(cref)
            self.body(st.body)
            for ref in reversed(pushed):
                self.pop(ref)
        elif isinstance(st, ast.While):
            acq = self.expr(st.test, collect_acquires=True)
            self.body(st.body)
            self.body(st.orelse)
            # `while not lock.acquire(timeout=..): ...` — after the loop
            # exits, the lock is held for the remainder of the method
            for ref, line in acq:
                self.push(ref, line)
        elif isinstance(st, ast.If):
            acq = self.expr(st.test, collect_acquires=True)
            for ref, line in acq:
                self.push(ref, line)
            self.body(st.body)
            for ref, _ in reversed(acq):
                self.pop(ref)
            self.body(st.orelse)
        elif isinstance(st, ast.For):
            self.expr(st.iter)
            self.body(st.body)
            self.body(st.orelse)
        elif isinstance(st, ast.Try):
            self.body(st.body)
            for h in st.handlers:
                self.body(h.body)
            self.body(st.orelse)
            self.body(st.finalbody)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs later (thread target / callback) — record
            # as a separate externally-entered pseudo-method
            self.nested.append((st.name, st))  # type: ignore[arg-type]
        elif isinstance(st, ast.ClassDef):
            pass
        elif isinstance(st, ast.Assign):
            got = _find_lock_call(st.value)
            if got is not None and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                kind, line = got
                name = st.targets[0].id
                self.local_locks[name] = LockInfo(kind, line)
                self.cm.local_lock_sites.setdefault(
                    line, f"{self.cm.qual}.{self.facts.name}.{name}"
                )
            elif len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                tname = self._value_type(st.value)
                if tname is not None:
                    self.local_types[st.targets[0].id] = tname
            for tgt in st.targets:
                self.target(tgt)
            self.expr(st.value)
        elif isinstance(st, ast.AugAssign):
            self.target(st.target, also_read=True)
            self.expr(st.value)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self.expr(st.value)
            self.target(st.target)
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self.expr(child)
                elif isinstance(child, ast.stmt):
                    self.stmt(child)

    def target(self, tgt: ast.expr, also_read: bool = False) -> None:
        if isinstance(tgt, ast.Attribute) \
                and isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            self.facts.accesses.append(AccessFact(
                tgt.attr, True, tgt.lineno, tuple(self.held)
            ))
            if also_read:
                self.facts.accesses.append(AccessFact(
                    tgt.attr, False, tgt.lineno, tuple(self.held)
                ))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self.target(el, also_read=also_read)
        elif isinstance(tgt, (ast.Subscript, ast.Starred, ast.Attribute)):
            # self._x[k] = v reads self._x
            self.expr(tgt.value if not isinstance(tgt, ast.Starred)
                      else tgt.value)

    # -- expressions -----------------------------------------------------

    def expr(self, e: ast.expr | None, collect_acquires: bool = False
             ) -> list[tuple[LockRef, int]]:
        acquired: list[tuple[LockRef, int]] = []
        if e is None:
            return acquired
        for node in ast.walk(e):
            if isinstance(node, ast.Call):
                self._call(node, acquired, collect_acquires)
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and isinstance(node.ctx, ast.Load):
                self.facts.accesses.append(AccessFact(
                    node.attr, False, node.lineno, tuple(self.held)
                ))
            elif isinstance(node, (ast.Lambda,)):
                pass  # body nodes reached by ast.walk; treated inline
        return acquired

    def _call(self, node: ast.Call, acquired: list[tuple[LockRef, int]],
              collect_acquires: bool) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            ref = self.lock_ref(fn.value)
            if ref is None and isinstance(fn.value, ast.Name):
                # module-level lock? leave to the pass (needs module ctx);
                # record the dotted call below instead
                pass
            if ref is not None:
                if fn.attr == "acquire":
                    if collect_acquires:
                        acquired.append((ref, node.lineno))
                    else:
                        self.push(ref, node.lineno)
                    return
                if fn.attr == "release":
                    self.pop(ref)
                    return
                # cond.wait()/notify()/locked() — not an ordering event
                return
        dotted = dotted_name(fn)
        recv_type = None
        if dotted is not None and "." in dotted:
            head = dotted.split(".")[0]
            recv_type = self.local_types.get(head)
        self.facts.calls.append(
            CallFact(dotted, node, node.lineno, tuple(self.held),
                     recv_type=recv_type)
        )


def _walk_method(cm: ClassModel, name: str, fn: ast.FunctionDef,
                 proj: "Project | None" = None,
                 mm: "ModuleModel | None" = None) -> None:
    facts = MethodFacts(
        name=name,
        entry_public=not name.startswith("_") or _is_dunder(name),
    )
    param_types: dict[str, str] = {}
    for a in list(fn.args.posonlyargs) + list(fn.args.args) \
            + list(fn.args.kwonlyargs):
        tname = _annotation_type_name(a.annotation)
        if tname is not None:
            param_types[a.arg] = tname
    w = _Walk(cm, facts, param_types, proj, mm)
    w.body(fn.body)
    cm.facts[name] = facts
    for nested_name, nested_fn in w.nested:
        pseudo = f"{name}.<locals>.{nested_name}"
        nested_facts = MethodFacts(name=pseudo, entry_public=True)
        nw = _Walk(cm, nested_facts, param_types, proj, mm)
        nw.local_locks = dict(w.local_locks)
        nw.local_types.update(w.local_types)
        nw.body(nested_fn.body)
        cm.facts[pseudo] = nested_facts


def _is_dunder(name: str) -> bool:
    return name.startswith("__") and name.endswith("__")


# ---------------------------------------------------------------------------
# Lock node naming + creation-site map
# ---------------------------------------------------------------------------


def lock_node_id(proj: Project, cm: ClassModel, ref: LockRef,
                 method: str) -> str | None:
    if ref.scope == "other":
        return ref.name
    if ref.scope == "self":
        info = cm.lock_attrs.get(ref.name)
        if info is not None and info.owner_qual is not None:
            return f"{info.owner_qual}.{ref.name}"
        return cm.lock_node(ref.name)
    if ref.scope == "module":
        return f"{cm.module}.{ref.name}"
    if ref.scope == "local":
        return f"{cm.qual}.{method}.{ref.name}"
    return None


def creation_sites(proj: Project) -> dict[tuple[str, int], str]:
    """(rel-path, line of the threading.X() call) -> lock node id, for
    every statically known lock creation. The runtime witness keys live
    locks by the frame that allocated them and uses this map to name
    them."""
    sites: dict[tuple[str, int], str] = {}
    for mm in proj.modules.values():
        rel = mm.sf.rel
        for name, info in mm.module_locks.items():
            if info.site_line is not None:
                sites[(rel, info.site_line)] = f"{mm.sf.module}.{name}"
        for cm in mm.classes.values():
            for attr, info in cm.lock_attrs.items():
                # inherited copies (owner_qual set) would mis-name the
                # site after the LAST subclass — only the defining class
                # owns the creation site
                if info.site_line is not None and info.owner_qual is None:
                    sites[(cm.rel, info.site_line)] = cm.lock_node(attr)
            for line, node in cm.local_lock_sites.items():
                sites.setdefault((cm.rel, line), node)
    return sites
