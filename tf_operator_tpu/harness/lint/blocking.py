"""Pass ``blocking-under-lock``: no slow calls inside a lock body.

The watchdog-heartbeat (PR 7) and probe-sweep (PR 9) contracts both
reduce to the same rule: state locks are held for bookkeeping, never
across anything that can stall — a sleep, a subprocess, an HTTP send,
joining a thread, waiting on an Event, or a device call. A blocked
lock-holder stalls every thread behind it and turns a latency blip
into a watchdog restart.

Matched categories while any lock/condvar is held:

- ``time.sleep``
- ``subprocess.*`` process launches
- HTTP/socket sends: ``urllib.request.urlopen``, ``*.urlopen``,
  ``*.getresponse``, ``socket.create_connection``, ``requests.*``
- ``Thread.join`` on attributes/locals typed ``threading.Thread``
- ``Event.wait`` on attributes typed ``threading.Event``
  (``Condition.wait`` is fine — it releases the lock)
- device calls: ``jax.device_put/device_get``, ``*.block_until_ready``
  — EXCEPT under a lock whose name contains ``device``: a coarse
  device mutex exists precisely to serialize device work (the serve
  scheduler's ``_device_lock`` contract).
"""

from __future__ import annotations

from tf_operator_tpu.harness.checks import Problem
from tf_operator_tpu.harness.lint import classmodel as cmod
from tf_operator_tpu.harness.lint.base import SourceFile, problem

PASS_ID = "blocking-under-lock"
DOC = ("no sleeps, subprocess launches, HTTP sends, thread joins, Event "
       "waits, or device calls while holding a lock/condvar")

_EXACT = {
    "time.sleep": "time.sleep",
    "urllib.request.urlopen": "HTTP send",
    "urlrequest.urlopen": "HTTP send",
    "socket.create_connection": "socket connect",
    "jax.device_put": "device call",
    "jax.device_get": "device call",
    "jax.block_until_ready": "device call",
}
_PREFIXES = {
    "subprocess.": "subprocess launch",
    "requests.": "HTTP send",
}
_SUFFIXES = {
    ".block_until_ready": "device call",
    ".getresponse": "HTTP response wait",
}
_DEVICE_CATEGORIES = {"device call"}


def _category(dotted: str) -> str | None:
    hit = _EXACT.get(dotted)
    if hit is not None:
        return hit
    for pre, cat in _PREFIXES.items():
        if dotted.startswith(pre):
            return cat
    for suf, cat in _SUFFIXES.items():
        if dotted.endswith(suf):
            return cat
    return None


def _typed_call_category(cm: cmod.ClassModel, dotted: str) -> str | None:
    """Thread.join / Event.wait recognized through attribute types."""
    parts = dotted.split(".")
    if len(parts) == 3 and parts[0] == "self":
        attr, meth = parts[1], parts[2]
        if meth == "join" and attr in cm.thread_attrs:
            return "Thread.join"
        if meth == "wait" and attr in cm.event_attrs:
            return "Event.wait"
    return None


def _held_all_device(cm: cmod.ClassModel,
                     held: tuple[cmod.LockRef, ...]) -> bool:
    return bool(held) and all("device" in r.name for r in held)


def run(files: list[SourceFile], proj: cmod.Project) -> list[Problem]:
    problems: list[Problem] = []
    by_rel = {sf.rel: sf for sf in files}
    for mm in proj.modules.values():
        sf = by_rel.get(mm.sf.rel)
        if sf is None:
            continue
        for cm in mm.classes.values():
            for facts in cm.facts.values():
                for call in facts.calls:
                    if not call.held or call.dotted is None:
                        continue
                    cat = _category(call.dotted) \
                        or _typed_call_category(cm, call.dotted)
                    if cat is None:
                        continue
                    if cat in _DEVICE_CATEGORIES \
                            and _held_all_device(cm, call.held):
                        continue
                    locks = ", ".join(r.name for r in call.held)
                    problems.append(problem(
                        sf, call.line, PASS_ID,
                        f"{cat} ({call.dotted}) while holding {locks} — "
                        "move the blocking call outside the lock body",
                    ))
                # one-level cross-class: a held-lock call into a typed
                # attribute whose method directly blocks
                for call in facts.calls:
                    if not call.held or call.dotted is None:
                        continue
                    parts = call.dotted.split(".")
                    if len(parts) != 3 or parts[0] != "self":
                        continue
                    tname = cm.attr_types.get(parts[1])
                    if tname is None:
                        continue
                    tcm = proj.resolve_type(mm, tname)
                    if tcm is None:
                        continue
                    tfacts = tcm.facts.get(parts[2])
                    if tfacts is None:
                        continue
                    for sub in tfacts.calls:
                        cat = sub.dotted and _category(sub.dotted)
                        if not cat:
                            continue
                        if cat in _DEVICE_CATEGORIES \
                                and _held_all_device(cm, call.held):
                            continue
                        locks = ", ".join(r.name for r in call.held)
                        problems.append(problem(
                            sf, call.line, PASS_ID,
                            f"call into {tname}.{parts[2]} (which does a "
                            f"{cat}: {sub.dotted}) while holding {locks}",
                        ))
                        break
    return problems
