"""Pass ``metrics-registry``: the ``tpu_*`` family discipline.

The metrics registry is process-global (``runtime/metrics.py
REGISTRY``), which makes two rules load-bearing:

1. **Declared once.** Every ``tpu_*`` family is registered against the
   global ``REGISTRY`` at exactly one site, with one kind and one label
   set; a second registration site is where label drift starts (the
   registry itself only catches exact-duplicate mismatches at import
   time of the *second* module). Label keywords at ``.inc/.set/...``
   call sites must match the declared label set exactly.

2. **Windowed reads in tests.** Because families survive across tests
   in one process, a test asserting on an absolute histogram quantile
   or comparing a counter's absolute ``.value()`` to a literal is
   order-dependent: histogram reads must window via
   ``snapshot()``/``quantile(since=...)`` and counter asserts must be
   before/after deltas (the PR 3/11 rules).

Local ``Registry()`` instances (unit tests of the registry itself) are
out of scope — only the global ``REGISTRY`` is the shared surface.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from tf_operator_tpu.harness.checks import Problem
from tf_operator_tpu.harness.lint import classmodel as cmod
from tf_operator_tpu.harness.lint.base import SourceFile, dotted_name, problem

PASS_ID = "metrics-registry"
DOC = ("each tpu_* family declared once against the global REGISTRY with "
       "one label set; call-site labels match; test reads are windowed")

_METRICS_MODULE = "tf_operator_tpu.runtime.metrics"
_DECL_METHODS = {"counter", "gauge", "histogram"}
_NON_LABEL_KWARGS = {"amount", "value", "since", "q", "buckets"}
_USE_METHODS = {"inc", "dec", "set", "observe", "value", "quantile",
                "snapshot"}


@dataclass
class Family:
    name: str
    kind: str
    labels: tuple[str, ...] | None   # None = not statically evaluable
    rel: str
    line: int


def _static_str_tuple(node: ast.expr | None) -> tuple[str, ...] | None:
    if node is None:
        return ()
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
            else:
                return None
        return tuple(out)
    return None


def _is_global_registry(expr: ast.expr, mm: cmod.ModuleModel) -> bool:
    d = dotted_name(expr)
    if d is None:
        return False
    if d == "REGISTRY":
        return mm.imports.get("REGISTRY", "").endswith("metrics.REGISTRY") \
            or mm.sf.module == _METRICS_MODULE
    resolved = mm.imports.get(d.split(".")[0])
    if resolved is None:
        return False
    full = d.replace(d.split(".")[0], resolved, 1)
    return full.endswith("metrics.REGISTRY")


def _collect_declarations(files: list[SourceFile], proj: cmod.Project
                          ) -> tuple[list[Family], dict[str, Family]]:
    fams: list[Family] = []
    by_const: dict[str, Family] = {}   # "<module>.<CONST>" -> family
    for mm in proj.modules.values():
        sf = mm.sf
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _DECL_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("tpu_")):
                continue
            if not _is_global_registry(node.func.value, mm):
                continue
            label_arg: ast.expr | None = None
            if len(node.args) >= 3:
                label_arg = node.args[2]
            for kw in node.keywords:
                if kw.arg == "labelnames":
                    label_arg = kw.value
            fam = Family(
                name=node.args[0].value, kind=node.func.attr,
                labels=_static_str_tuple(label_arg),
                rel=sf.rel, line=node.lineno,
            )
            fams.append(fam)
        # map module-level constants to families for call-site checks
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                for fam in fams:
                    if fam.rel == sf.rel and fam.line == node.lineno:
                        by_const[f"{sf.module}.{node.targets[0].id}"] = fam
    return fams, by_const


def _resolve_const(mm: cmod.ModuleModel, expr: ast.expr,
                   by_const: dict[str, Family]) -> Family | None:
    d = dotted_name(expr)
    if d is None:
        return None
    if d in mm.imports:
        return by_const.get(mm.imports[d])
    head = d.split(".")[0]
    if head in mm.imports and "." in d:
        return by_const.get(d.replace(head, mm.imports[head], 1))
    return by_const.get(f"{mm.sf.module}.{d}")


def run(files: list[SourceFile], proj: cmod.Project) -> list[Problem]:
    problems: list[Problem] = []
    by_rel = {sf.rel: sf for sf in files}
    fams, by_const = _collect_declarations(files, proj)
    # -- declared once, consistently ------------------------------------
    seen: dict[str, Family] = {}
    for fam in sorted(fams, key=lambda f: (f.rel, f.line)):
        first = seen.get(fam.name)
        if first is None:
            seen[fam.name] = fam
            continue
        sf = by_rel.get(fam.rel)
        if sf is None:
            continue
        what = "re-declared"
        if first.kind != fam.kind:
            what = f"re-declared as {fam.kind} (was {first.kind})"
        elif first.labels != fam.labels:
            what = (f"re-declared with labels {list(fam.labels or ())} "
                    f"(was {list(first.labels or ())})")
        problems.append(problem(
            sf, fam.line, PASS_ID,
            f"family {fam.name} {what} — first declared at "
            f"{first.rel}:{first.line}; declare each tpu_* family once",
        ))
    # -- call-site label discipline + windowed test reads ----------------
    for mm in proj.modules.values():
        sf = mm.sf
        if sf.tree is None or (sf_rel := by_rel.get(sf.rel)) is None:
            continue
        in_tests = sf.rel.startswith("tests/")
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _USE_METHODS):
                continue
            fam = _resolve_const(mm, node.func.value, by_const)
            if fam is None:
                continue
            meth = node.func.attr
            if fam.labels is not None and not any(
                    kw.arg is None for kw in node.keywords):
                label_kwargs = {
                    kw.arg for kw in node.keywords
                    if kw.arg not in _NON_LABEL_KWARGS
                }
                declared = set(fam.labels)
                if meth in ("inc", "dec", "set", "observe", "value") \
                        and label_kwargs != declared:
                    problems.append(problem(
                        sf_rel, node.lineno, PASS_ID,
                        f"{fam.name}.{meth}() labels "
                        f"{sorted(label_kwargs)} != declared "
                        f"{sorted(declared)} ({fam.rel}:{fam.line})",
                    ))
            if in_tests and meth == "quantile" and fam.kind == "histogram":
                if not any(kw.arg == "since" for kw in node.keywords):
                    problems.append(problem(
                        sf_rel, node.lineno, PASS_ID,
                        f"{fam.name}.quantile() in a test without "
                        "since= — window histogram reads via "
                        "snapshot()/quantile(since=...) (the registry "
                        "is process-global)",
                    ))
        if in_tests:
            problems.extend(_absolute_counter_asserts(
                sf_rel, mm, by_const))
    return problems


def _absolute_counter_asserts(sf: SourceFile, mm: cmod.ModuleModel,
                              by_const: dict[str, Family]) -> list[Problem]:
    """``FAM.value() == 3`` in a test: order-dependent absolute read."""
    out: list[Problem] = []
    if mm.sf.tree is None:
        return out
    for node in ast.walk(mm.sf.tree):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        has_literal = any(
            isinstance(s, ast.Constant) and isinstance(s.value, (int, float))
            and not isinstance(s.value, bool) for s in sides
        )
        if not has_literal:
            continue
        for s in sides:
            if not (isinstance(s, ast.Call)
                    and isinstance(s.func, ast.Attribute)
                    and s.func.attr == "value"):
                continue
            fam = _resolve_const(mm, s.func.value, by_const)
            if fam is None or fam.kind != "counter":
                continue
            if all(isinstance(op, ast.Eq) for op in node.ops):
                out.append(problem(
                    sf, node.lineno, PASS_ID,
                    f"absolute {fam.name}.value() == literal in a test — "
                    "counters are process-global; assert before/after "
                    "deltas instead",
                ))
    return out
