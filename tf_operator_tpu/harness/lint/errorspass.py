"""Pass ``typed-error``: the ServeError wire-code vocabulary.

PR 7's contract is that every client-visible failure carries a ``code``
from one taxonomy (``serve/resilience.py`` — the ``ServeError``
subclasses plus the transport codes the router mints), because the
fleet router *dispatches on those strings* (retry elsewhere / eject /
give up) and a typo'd or undeclared code silently downgrades to
"not retryable".

Checked across the tree:

- a class subclassing a taxonomy error outside ``resilience.py`` must
  not mint a ``code`` the taxonomy doesn't know;
- every string literal compared against a code-valued expression
  (``payload["code"] == ...``, ``.get("code") in (...)``, ``err.code``)
  must be a known code;
- every ``{"code": "..."}`` payload literal must use a known code;
- module-level code-set constants used in ``code in NAME`` dispatch
  (e.g. the router's ``RETRY_ELSEWHERE``) must contain only known codes.

The vocabulary = ``code`` class attrs of ``ServeError`` subclasses in
``serve/resilience.py`` + its ``WIRE_CODES`` constant + ``internal``.
"""

from __future__ import annotations

import ast

from tf_operator_tpu.harness.checks import Problem
from tf_operator_tpu.harness.lint import classmodel as cmod
from tf_operator_tpu.harness.lint.base import SourceFile, dotted_name, problem

PASS_ID = "typed-error"
DOC = ("every ServeError subclass / code literal / code-set constant uses "
       "a code declared in the serve/resilience.py taxonomy")

_TAXONOMY_MODULE = "tf_operator_tpu.serve.resilience"


def _taxonomy(proj: cmod.Project) -> tuple[set[str], set[str]]:
    """(known codes, taxonomy class names) from resilience.py."""
    codes = {"internal"}
    class_names: set[str] = set()
    mm = proj.modules.get(_TAXONOMY_MODULE)
    if mm is None or mm.sf.tree is None:
        return codes, class_names
    # transitive ServeError descendants within the module
    bases: dict[str, tuple[str, ...]] = {}
    code_attr: dict[str, str] = {}
    for node in mm.sf.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        bases[node.name] = tuple(
            d for d in (dotted_name(b) for b in node.bases) if d
        )
        for item in node.body:
            if isinstance(item, ast.Assign) and len(item.targets) == 1 \
                    and isinstance(item.targets[0], ast.Name) \
                    and item.targets[0].id == "code" \
                    and isinstance(item.value, ast.Constant) \
                    and isinstance(item.value.value, str):
                code_attr[node.name] = item.value.value
    descendants = {"ServeError"}
    changed = True
    while changed:
        changed = False
        for name, bs in bases.items():
            if name not in descendants and any(b in descendants for b in bs):
                descendants.add(name)
                changed = True
    class_names = descendants & set(bases)
    for name in class_names:
        if name in code_attr:
            codes.add(code_attr[name])
    # WIRE_CODES: the transport codes minted outside ServeError raises
    for node in mm.sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "WIRE_CODES":
            got = _str_elements(node.value)
            if got is not None:
                codes.update(got)
    return codes, class_names


def _str_elements(node: ast.expr) -> set[str] | None:
    if isinstance(node, ast.Call) and dotted_name(node.func) in (
            "frozenset", "set", "tuple") and node.args:
        return _str_elements(node.args[0])
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
            else:
                return None
        return out
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        left = _str_elements(node.left)
        right = _str_elements(node.right)
        if left is not None and right is not None:
            return left | right
    return None


def _is_code_expr(e: ast.expr) -> bool:
    if isinstance(e, ast.Subscript) \
            and isinstance(e.slice, ast.Constant) and e.slice.value == "code":
        return True
    if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute) \
            and e.func.attr == "get" and e.args \
            and isinstance(e.args[0], ast.Constant) \
            and e.args[0].value == "code":
        return True
    if isinstance(e, ast.Attribute) and e.attr == "code":
        return True
    return False


def run(files: list[SourceFile], proj: cmod.Project) -> list[Problem]:
    problems: list[Problem] = []
    codes, taxonomy_classes = _taxonomy(proj)
    if not taxonomy_classes:
        return problems   # no taxonomy in tree (fixture runs)
    by_rel = {sf.rel: sf for sf in files}
    for mm in proj.modules.values():
        sf = by_rel.get(mm.sf.rel)
        if sf is None or sf.tree is None:
            continue
        in_taxonomy = mm.sf.module == _TAXONOMY_MODULE
        code_set_names: set[str] = set()
        for node in ast.walk(sf.tree):
            # subclasses minting unknown codes
            if isinstance(node, ast.ClassDef) and not in_taxonomy:
                base_names = {
                    (dotted_name(b) or "").split(".")[-1]
                    for b in node.bases
                }
                if base_names & taxonomy_classes:
                    for item in node.body:
                        if isinstance(item, ast.Assign) \
                                and len(item.targets) == 1 \
                                and isinstance(item.targets[0], ast.Name) \
                                and item.targets[0].id == "code" \
                                and isinstance(item.value, ast.Constant) \
                                and isinstance(item.value.value, str) \
                                and item.value.value not in codes:
                            problems.append(problem(
                                sf, item.lineno, PASS_ID,
                                f"ServeError subclass {node.name} mints "
                                f"unknown code {item.value.value!r} — "
                                "declare it in serve/resilience.py "
                                "(taxonomy / WIRE_CODES)",
                            ))
            # comparisons against code-valued expressions
            if isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                if any(_is_code_expr(s) for s in sides):
                    for s, op in zip(node.comparators, node.ops):
                        if isinstance(op, ast.In):
                            if isinstance(s, ast.Name):
                                code_set_names.add(s.id)
                                continue
                            got = _str_elements(s)
                            for val in sorted(got or ()):
                                if val not in codes:
                                    problems.append(_unknown(
                                        sf, s.lineno, val))
                    for s in sides:
                        if isinstance(s, ast.Constant) \
                                and isinstance(s.value, str) \
                                and s.value not in codes:
                            problems.append(_unknown(sf, node.lineno,
                                                     s.value))
            # payload literals minting codes
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and k.value == "code" \
                            and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str) \
                            and v.value not in codes:
                        problems.append(_unknown(sf, v.lineno, v.value))
        # code-set constants dispatched on via `code in NAME`
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id in code_set_names:
                got = _str_elements(node.value)
                for val in sorted(got or ()):
                    if val not in codes:
                        problems.append(_unknown(sf, node.lineno, val))
    return problems


def _unknown(sf: SourceFile, line: int, val: str) -> Problem:
    return problem(
        sf, line, PASS_ID,
        f"unknown serve error code {val!r} — the router dispatches on "
        "these strings; declare it in the serve/resilience.py taxonomy "
        "or WIRE_CODES",
    )
