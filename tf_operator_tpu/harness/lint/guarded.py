"""Pass ``guarded-attr``: mixed lock discipline on instance attributes.

Within a class that owns at least one lock, an attribute that is
*written while holding a lock* in some method (outside ``__init__``)
is a guarded attribute: every other read or write of it from a
different thread needs the same lock. The pass flags accesses that can
execute with **no** lock held.

Precision machinery:

- ``__init__`` (and other dunder construction paths) is exempt —
  construction is single-threaded by contract.
- Entry-context propagation: a private helper only ever invoked from
  inside ``with self._lock`` bodies inherits that lock, so accesses in
  ``_retire_locked``-style helpers are not false positives. A method
  reachable with an empty held-set anywhere (public methods, thread
  targets, unreferenced helpers) keeps the empty context.
- Lock/Condition/Event/Thread attributes, method names, and
  ``Final``-style set-once-in-init attributes (never written under a
  lock outside init) are not findings.

Benign lock-free reads (approximate stats for logs/metrics) are
expected to carry a per-line waiver naming the reason.
"""

from __future__ import annotations

from tf_operator_tpu.harness.checks import Problem
from tf_operator_tpu.harness.lint import classmodel as cmod
from tf_operator_tpu.harness.lint.base import SourceFile, problem

PASS_ID = "guarded-attr"
DOC = ("attributes written under a lock in some methods of a class must "
       "not be read/written lock-free in others")

_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__", "__del__"}


def _entry_contexts(cm: cmod.ClassModel) -> dict[str, set[frozenset[str]]]:
    """Possible held-lock sets (self-lock attr names only) at entry of
    each method, via fixpoint over internal self-calls."""
    entries: dict[str, set[frozenset[str]]] = {}
    called_internally: set[str] = set()
    for facts in cm.facts.values():
        for call in facts.calls:
            if call.dotted and call.dotted.startswith("self.") \
                    and call.dotted.count(".") == 1:
                called_internally.add(call.dotted.split(".")[1])
    for name, facts in cm.facts.items():
        if facts.entry_public or name not in called_internally:
            entries[name] = {frozenset()}
        else:
            entries[name] = set()
    for _ in range(6):  # small fixpoint; call chains are shallow
        changed = False
        for name, facts in cm.facts.items():
            # iterate only contexts actually established so far — a
            # substituted empty context here would propagate a spurious
            # "callable lock-free" fact down two-hop locked chains and
            # never retract (contexts only grow)
            for ctx in set(entries.get(name, set())):
                for call in facts.calls:
                    if not (call.dotted and call.dotted.startswith("self.")
                            and call.dotted.count(".") == 1):
                        continue
                    callee = call.dotted.split(".")[1]
                    if callee not in entries:
                        continue
                    held = ctx | {
                        r.name for r in call.held if r.scope == "self"
                    }
                    if frozenset(held) not in entries[callee]:
                        entries[callee].add(frozenset(held))
                        changed = True
        if not changed:
            break
    for name in entries:
        if not entries[name]:
            entries[name] = {frozenset()}
    return entries


def run(files: list[SourceFile], proj: cmod.Project) -> list[Problem]:
    problems: list[Problem] = []
    by_rel = {sf.rel: sf for sf in files}
    for mm in proj.modules.values():
        sf = by_rel.get(mm.sf.rel)
        if sf is None:
            continue
        for cm in mm.classes.values():
            if cm.is_module_scope or not cm.lock_attrs:
                continue
            problems.extend(_check_class(sf, cm))
    return problems


def _check_class(sf: SourceFile, cm: cmod.ClassModel) -> list[Problem]:
    entries = _entry_contexts(cm)
    skip_attrs = (
        set(cm.lock_attrs) | cm.event_attrs | cm.thread_attrs
        | set(cm.methods)
    )

    def effective(facts: cmod.MethodFacts,
                  held: tuple[cmod.LockRef, ...]) -> list[frozenset[str]]:
        local = frozenset(r.name for r in held if r.scope == "self")
        # module/local locks also count as "some lock held"
        extra = frozenset(
            f"{r.scope}:{r.name}" for r in held if r.scope != "self"
        )
        return [ctx | local | extra for ctx in entries.get(
            facts.name, {frozenset()})]

    # 1) find guarded attrs: written under some lock outside init
    guarded: dict[str, tuple[str, str]] = {}  # attr -> (lock, method)
    for name, facts in cm.facts.items():
        base = name.split(".", 1)[0]
        if base in _EXEMPT_METHODS:
            continue
        for acc in facts.accesses:
            if not acc.is_write or acc.attr in skip_attrs:
                continue
            for ctx in effective(facts, acc.held):
                if ctx:
                    guarded.setdefault(
                        acc.attr, (sorted(ctx)[0], name)
                    )
    if not guarded:
        return []
    # 2) flag possibly-lock-free accesses to guarded attrs
    out: list[Problem] = []
    seen: set[tuple[str, int]] = set()
    for name, facts in cm.facts.items():
        base = name.split(".", 1)[0]
        if base in _EXEMPT_METHODS:
            continue
        for acc in facts.accesses:
            if acc.attr not in guarded:
                continue
            if all(ctx for ctx in effective(facts, acc.held)):
                continue  # every entry context holds some lock
            key = (acc.attr, acc.line)
            if key in seen:
                continue
            seen.add(key)
            lock, wmeth = guarded[acc.attr]
            verb = "written" if acc.is_write else "read"
            out.append(problem(
                sf, acc.line, PASS_ID,
                f"{cm.name}.{acc.attr} is guarded (written under "
                f"{lock} in {wmeth}) but {verb} lock-free in {name}",
            ))
    return out
