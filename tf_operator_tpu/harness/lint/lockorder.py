"""Pass ``lock-order``: the static "acquired while holding" graph.

Extracts, per class and across module boundaries (through attribute
types), every edge *L1 -> L2* = "lock L2 was acquired while L1 was
held", then fails on cycles — the static form of the classic deadlock
condition. Self-edges on plain ``Lock`` attributes (re-acquiring a
non-reentrant lock you already hold) are reported too; ``RLock`` and
``Condition`` (whose default inner lock is an RLock) self-edges are
legal re-entry and ignored.

The same graph is the contract for the runtime witness
(``tf_operator_tpu/runtime/lockwitness.py``): the chaos suites install
the witness, record the acquisition-order edges real threads actually
perform, and assert they form a subgraph of the transitive closure of
this graph — pinning the static model to the running system.

Public API (used by the witness tests and tools/lint_smoke.py):

- ``static_lock_graph(files) -> LockGraph`` with ``nodes``, ``edges``,
  ``sites`` ((rel, line) -> node), ``aliases`` (merged node unions).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tf_operator_tpu.harness.checks import Problem
from tf_operator_tpu.harness.lint import classmodel as cmod
from tf_operator_tpu.harness.lint.base import SourceFile, problem

PASS_ID = "lock-order"
DOC = ("extract the per-class/cross-module 'acquired while holding' lock "
       "graph and fail on cycles (and on re-acquiring a plain Lock)")

_MAX_CALL_DEPTH = 4


class _Union:
    """Union-find over lock node ids (constructor-param aliasing)."""

    def __init__(self) -> None:
        self.parent: dict[str, str] = {}

    def find(self, x: str) -> str:
        self.parent.setdefault(x, x)
        while self.parent[x] != x:
            self.parent[x] = self.parent[self.parent[x]]
            x = self.parent[x]
        return x

    def union(self, a: str, b: str) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            # deterministic representative: lexicographically smallest
            lo, hi = sorted((ra, rb))
            self.parent[hi] = lo


@dataclass
class LockGraph:
    nodes: set[str] = field(default_factory=set)
    # canonical edge -> one (rel, line) witness site for reporting
    edges: dict[tuple[str, str], tuple[str, int]] = field(
        default_factory=dict)
    sites: dict[tuple[str, int], str] = field(default_factory=dict)
    kinds: dict[str, str] = field(default_factory=dict)
    union: _Union = field(default_factory=_Union)

    def canon(self, node: str) -> str:
        return self.union.find(node)

    def closure(self) -> set[tuple[str, str]]:
        """Transitive closure of the edge set (the witness observes an
        edge from EVERY held lock to a new acquisition, so a chain
        A->B->C legally shows up as A->C at runtime)."""
        adj: dict[str, set[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, set()).add(b)
        out: set[tuple[str, str]] = set()
        for start in list(adj):
            seen: set[str] = set()
            stack = [start]
            while stack:
                cur = stack.pop()
                for nxt in adj.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            out.update((start, n) for n in seen)
        return out


def _node(proj: cmod.Project, cm: cmod.ClassModel, ref: cmod.LockRef,
          method: str) -> str | None:
    return cmod.lock_node_id(proj, cm, ref, method)


def _lock_kind(cm: cmod.ClassModel, ref: cmod.LockRef) -> str:
    if ref.kind is not None:
        return ref.kind
    if ref.scope == "self":
        info = cm.lock_attrs.get(ref.name)
    elif ref.scope == "module":
        info = cm.module_locks.get(ref.name)
    else:
        info = None
    return info.kind if info is not None else "lock"


def _collect_aliases(proj: cmod.Project, graph: LockGraph) -> None:
    """Merge nodes for the ctor-param hand-off idiom::

        B.__init__: self._y = y_param or threading.Lock()
        A: self._sub = B(..., y_param=self._x)   # A._x aliases B._y
    """
    # param name -> lock attr, per class
    param_attr: dict[str, dict[str, str]] = {}
    for cm in proj.classes.values():
        for attr, info in cm.lock_attrs.items():
            for p in info.alias_params:
                param_attr.setdefault(cm.qual, {})[p] = attr
    for mm in proj.modules.values():
        for cm in mm.classes.values():
            for facts in cm.facts.values():
                for call in facts.calls:
                    if call.dotted is None:
                        continue
                    target = proj.resolve_class(mm, call.dotted)
                    if target is None or target.qual not in param_attr:
                        continue
                    for kw in call.node.keywords:
                        if kw.arg is None:
                            continue
                        attr = param_attr[target.qual].get(kw.arg)
                        if attr is None:
                            continue
                        src = _self_lock_arg(cm, kw.value)
                        if src is not None:
                            graph.union.union(
                                cm.lock_node(src), target.lock_node(attr)
                            )


def _self_lock_arg(cm: cmod.ClassModel, expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and expr.attr in cm.lock_attrs:
        return expr.attr
    return None


def _reachable_acquires(proj: cmod.Project, mm: cmod.ModuleModel,
                        cm: cmod.ClassModel, method: str,
                        memo: dict[tuple[str, str], set[tuple[str, str]]],
                        depth: int = 0,
                        stack: frozenset[tuple[str, str]] = frozenset(),
                        ) -> set[tuple[str, str]]:
    """Lock nodes (with kind) acquired anywhere in the call tree rooted
    at (class, method) — what a caller holding a lock is exposed to."""
    key = (cm.qual, method)
    if key in memo:
        return memo[key]
    if key in stack or depth > _MAX_CALL_DEPTH:
        return set()
    facts = cm.facts.get(method)
    if facts is None:
        return set()
    out: set[tuple[str, str]] = set()
    for acq in facts.acquires:
        node = _node(proj, cm, acq.ref, method)
        if node is not None:
            out.add((node, _lock_kind(cm, acq.ref)))
    nstack = stack | {key}
    for call in facts.calls:
        for tgt_cm, tgt_mm, tgt_meth in _resolve_call(proj, mm, cm, call):
            out |= _reachable_acquires(
                proj, tgt_mm, tgt_cm, tgt_meth, memo, depth + 1, nstack
            )
    memo[key] = out
    return out


def _resolve_call(proj: cmod.Project, mm: cmod.ModuleModel,
                  cm: cmod.ClassModel, call: cmod.CallFact,
                  ) -> list[tuple[cmod.ClassModel, cmod.ModuleModel, str]]:
    """CallFact -> [(class, module, method)] targets we can follow."""
    d = call.dotted
    if d is None:
        return []
    parts = d.split(".")
    out: list[tuple[cmod.ClassModel, cmod.ModuleModel, str]] = []
    # typed param/local receiver: sched.fence_and_harvest() with
    # sched: ContinuousScheduler
    if call.recv_type is not None and len(parts) == 2:
        tcm = proj.resolve_type(mm, call.recv_type)
        if tcm is not None:
            owner = cmod.method_owner(proj, tcm, parts[1])
            if owner is not None:
                omm = proj.modules.get(owner.module)
                if omm is not None:
                    return [(owner, omm, parts[1])]
    if parts[0] == "self" and not cm.is_module_scope:
        if len(parts) == 2:
            owner = cmod.method_owner(proj, cm, parts[1])
            if owner is not None:
                omm = proj.modules.get(owner.module)
                if omm is not None:
                    out.append((owner, omm, parts[1]))
        elif len(parts) == 3:
            attr, meth = parts[1], parts[2]
            tname = cm.attr_types.get(attr)
            if tname is not None:
                tcm = proj.resolve_type(mm, tname)
                if tcm is not None:
                    owner = cmod.method_owner(proj, tcm, meth)
                    if owner is not None:
                        omm = proj.modules.get(owner.module)
                        if omm is not None:
                            out.append((owner, omm, meth))
        elif len(parts) == 4:
            # self.server.cluster.replace(...) — two typed hops (the
            # handler -> stub -> backing store chain)
            t1 = cm.attr_types.get(parts[1])
            c1 = proj.resolve_type(mm, t1) if t1 else None
            if c1 is not None:
                m1 = proj.modules.get(c1.module)
                t2 = c1.attr_types.get(parts[2])
                c2 = proj.resolve_type(m1, t2) if t2 and m1 else None
                if c2 is not None:
                    owner = cmod.method_owner(proj, c2, parts[3])
                    if owner is not None:
                        omm = proj.modules.get(owner.module)
                        if omm is not None:
                            out.append((owner, omm, parts[3]))
        return out
    # direct constructor call: ClassName(...) runs __init__
    tcm = proj.resolve_class(mm, d)
    if tcm is not None and "__init__" in tcm.facts:
        tmm = proj.modules.get(tcm.module)
        if tmm is not None:
            out.append((tcm, tmm, "__init__"))
        return out
    # module-level function call, same module or imported
    if len(parts) == 1:
        mscope = mm.classes.get("<module>")
        if mscope is not None and parts[0] in mscope.facts:
            out.append((mscope, mm, parts[0]))
        return out
    # CONSTANT.meth(...) on a module-level instance (REGISTRY, metric
    # families, SERVE_TRACER, ...), local or imported
    if len(parts) == 2:
        const, meth = parts
        tname = mm.global_types.get(const)
        owner_mm = mm
        if tname is None and const in mm.imports:
            target = mm.imports[const]
            owner_mod, _, owner_name = target.rpartition(".")
            owner_mm = proj.modules.get(owner_mod)  # type: ignore[assignment]
            if owner_mm is not None:
                tname = owner_mm.global_types.get(owner_name)
        if tname is not None and owner_mm is not None:
            tcm = proj.resolve_class(owner_mm, tname)
            if tcm is not None:
                owner = cmod.method_owner(proj, tcm, meth)
                if owner is not None:
                    tmm = proj.modules.get(owner.module)
                    if tmm is not None:
                        out.append((owner, tmm, meth))
    return out


def build_graph(files: list[SourceFile],
                proj: cmod.Project | None = None) -> LockGraph:
    proj = proj or cmod.build_project(files)
    graph = LockGraph()
    graph.sites = cmod.creation_sites(proj)
    _collect_aliases(proj, graph)
    # register nodes + kinds
    for mm in proj.modules.values():
        for name, info in mm.module_locks.items():
            nid = graph.canon(f"{mm.sf.module}.{name}")
            graph.nodes.add(nid)
            graph.kinds[nid] = info.kind
        for cm in mm.classes.values():
            for attr, info in cm.lock_attrs.items():
                nid = graph.canon(cm.lock_node(attr))
                graph.nodes.add(nid)
                # an rlock/condition kind wins over plain lock on merge
                prev = graph.kinds.get(nid)
                if prev is None or prev == "lock":
                    graph.kinds[nid] = info.kind
    # canonicalize creation sites
    graph.sites = {k: graph.canon(v) for k, v in graph.sites.items()}
    memo: dict[tuple[str, str], set[tuple[str, str]]] = {}
    for mm in proj.modules.values():
        for cm in mm.classes.values():
            for method, facts in cm.facts.items():
                for acq in facts.acquires:
                    tgt = _node(proj, cm, acq.ref, method)
                    if tgt is None:
                        continue
                    tgt = graph.canon(tgt)
                    graph.nodes.add(tgt)
                    graph.kinds.setdefault(tgt, _lock_kind(cm, acq.ref))
                    for held in acq.held:
                        src = _node(proj, cm, held, method)
                        if src is None:
                            continue
                        src = graph.canon(src)
                        graph.edges.setdefault(
                            (src, tgt), (cm.rel, acq.line)
                        )
                for call in facts.calls:
                    if not call.held:
                        continue
                    for tgt_cm, tgt_mm, tgt_meth in _resolve_call(
                            proj, mm, cm, call):
                        reach = _reachable_acquires(
                            proj, tgt_mm, tgt_cm, tgt_meth, memo
                        )
                        for node, _kind in reach:
                            tgt = graph.canon(node)
                            graph.nodes.add(tgt)
                            for held in call.held:
                                src = _node(proj, cm, held, method)
                                if src is None:
                                    continue
                                src = graph.canon(src)
                                graph.edges.setdefault(
                                    (src, tgt), (cm.rel, call.line)
                                )
    return graph


def static_lock_graph(files: list[SourceFile]) -> LockGraph:
    """The witness-facing entry point (also used by tools)."""
    return build_graph(files)


def _cycles(graph: LockGraph) -> list[list[str]]:
    """Strongly connected components with >1 node, plus illegal
    self-loops; deterministic order."""
    adj: dict[str, list[str]] = {}
    for (a, b) in graph.edges:
        if a != b:
            adj.setdefault(a, []).append(b)
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    onstack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (the graph is small but recursion depth is
        # unbounded in principle)
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    # illegal self-loops: re-acquiring a held plain Lock
    for (a, b) in sorted(graph.edges):
        if a == b and graph.kinds.get(a, "lock") == "lock":
            out.append([a])
    return out


def run(files: list[SourceFile], proj: cmod.Project) -> list[Problem]:
    graph = build_graph(files, proj)
    problems: list[Problem] = []
    by_rel = {sf.rel: sf for sf in files}
    for comp in _cycles(graph):
        if len(comp) == 1:
            node = comp[0]
            rel, line = graph.edges[(node, node)]
            sf = by_rel.get(rel)
            if sf is None:
                continue
            problems.append(problem(
                sf, line, PASS_ID,
                f"non-reentrant lock {node} acquired while already held "
                "(self-deadlock; use RLock or restructure)",
            ))
            continue
        # anchor the report at each edge inside the cycle so a per-line
        # waiver must name the specific acquisition it blesses
        comp_set = set(comp)
        for (a, b), (rel, line) in sorted(graph.edges.items()):
            if a in comp_set and b in comp_set and a != b:
                sf = by_rel.get(rel)
                if sf is None:
                    continue
                problems.append(problem(
                    sf, line, PASS_ID,
                    "lock-order cycle through "
                    f"{' -> '.join(comp)}: this acquisition takes {b} "
                    f"while holding {a}",
                ))
    return problems
