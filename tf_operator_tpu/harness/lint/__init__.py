"""tpulint — project-specific static analysis passes for the checks gate.

``harness/checks.py`` runs the generic syntax/unused-import lints; this
package adds the passes that encode the repo's own concurrency and
discipline contracts (see docs/static-analysis.md for the catalog and
the waiver grammar):

- ``lock-order``        static "acquired while holding" graph, no cycles
- ``guarded-attr``      lock-guarded attributes never accessed lock-free
- ``blocking-under-lock`` no sleeps/HTTP/subprocess/joins/device calls
                          inside a lock body
- ``metrics-registry``  tpu_* families declared once, labels consistent,
                          test reads windowed
- ``typed-error``       ServeError codes come from the taxonomy

Every file is parsed once (``base.SourceFile``) and shared by all
passes; the whole-tree run stays well under the 15s CI budget.
"""

from __future__ import annotations

from tf_operator_tpu.harness.checks import Problem
from tf_operator_tpu.harness.lint import (
    blocking,
    errorspass,
    guarded,
    lockorder,
    metricspass,
)
from tf_operator_tpu.harness.lint import classmodel as cmod
from tf_operator_tpu.harness.lint.base import (
    SourceFile,
    apply_waivers,
    load_source_file,
    waiver_problems,
)

# ordered registry: (pass id, one-line doc, run(files, project) -> problems)
PASSES: tuple[tuple[str, str, object], ...] = (
    (lockorder.PASS_ID, lockorder.DOC, lockorder.run),
    (guarded.PASS_ID, guarded.DOC, guarded.run),
    (blocking.PASS_ID, blocking.DOC, blocking.run),
    (metricspass.PASS_ID, metricspass.DOC, metricspass.run),
    (errorspass.PASS_ID, errorspass.DOC, errorspass.run),
)

PASS_IDS: tuple[str, ...] = tuple(p[0] for p in PASSES)


def run_lint_passes(files: list[SourceFile],
                    select: tuple[str, ...] | None = None,
                    ) -> list[Problem]:
    """Run the project passes over pre-parsed files; waivers applied.

    ``select`` restricts to a subset of pass ids (the ``--select`` CLI);
    unknown ids raise so a typo'd selection can't silently pass."""
    if select:
        unknown = set(select) - set(PASS_IDS)
        if unknown:
            raise ValueError(
                f"unknown pass id(s): {sorted(unknown)}; "
                f"known: {list(PASS_IDS)}"
            )
    proj = cmod.build_project(files)
    by_rel = {sf.rel: sf for sf in files}
    problems: list[Problem] = []
    for pass_id, _doc, run in PASSES:
        if select and pass_id not in select:
            continue
        problems.extend(run(files, proj))  # type: ignore[operator]
    # per-line justified waivers (the only suppression mechanism)
    out: list[Problem] = []
    for p in problems:
        sf = by_rel.get(p.path)
        if sf is not None and p.pass_id in sf.waived_lines.get(p.line, ()):
            continue
        out.append(p)
    # malformed/unknown waivers are findings themselves
    known = set(PASS_IDS) | {"syntax", "unused-import"}
    for sf in files:
        out.extend(waiver_problems(sf, known))
    out.sort(key=lambda p: (p.path, p.line, p.pass_id))
    return out


__all__ = [
    "PASSES", "PASS_IDS", "run_lint_passes", "SourceFile",
    "load_source_file", "apply_waivers",
]
