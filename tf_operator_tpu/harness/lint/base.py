"""tpulint core: parsed-source model, waiver grammar, pass registry glue.

The project-specific passes (see ``tf_operator_tpu/harness/lint/``) extend
the ``harness.checks`` gate with concurrency/discipline analyses. Every
finding carries a pass id and can be waived ONLY per line, with a written
justification::

    # lint: ok lock-order — probe sweep snapshots under one lock by design

Grammar: ``# lint: ok <pass-id>[,<pass-id>...] <dash> <reason>`` where
``<dash>`` is ``—``/``–``/``-`` and ``<reason>`` is non-empty. A waiver
comment covers findings on its own physical line; a standalone waiver
comment line covers the line directly below it (for statements with no
trailing room). There is deliberately NO file- or pass-level blanket
ignore: an unjustified waiver is itself reported (pass id ``waiver``).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

from tf_operator_tpu.harness.checks import Problem

# ids: one or more pass ids separated by commas, spaces around commas
# allowed ("ok lock-order, guarded-attr — ..."); a bare dash after a
# space cannot extend the id list (extending requires a comma), so the
# reason separator stays unambiguous
_WAIVER_RE = re.compile(
    r"#\s*lint:\s*ok\s+"
    r"(?P<ids>[A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)"
    r"(?:\s*(?:—|–|--|-)\s*(?P<reason>.*))?"
)


@dataclass
class Waiver:
    line: int
    pass_ids: tuple[str, ...]
    reason: str


@dataclass
class SourceFile:
    """One parsed .py file shared by every pass (parse-once driver)."""

    path: str                      # absolute
    rel: str                       # root-relative, forward slashes
    src: str
    tree: ast.Module | None        # None on syntax error (reported elsewhere)
    waivers: list[Waiver] = field(default_factory=list)
    # line -> pass ids waived there (includes the line below standalone
    # waiver comment lines)
    waived_lines: dict[int, set[str]] = field(default_factory=dict)

    @property
    def module(self) -> str:
        """Dotted module name: tf_operator_tpu/serve/scheduler.py ->
        tf_operator_tpu.serve.scheduler; bench.py -> bench."""
        mod = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        mod = mod.replace("/", ".").replace("\\", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod


def load_source_file(path: str, root: str) -> SourceFile:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    try:
        tree = ast.parse(src)
    except SyntaxError:
        tree = None
    sf = SourceFile(path=path, rel=rel, src=src, tree=tree)
    _parse_waivers(sf)
    return sf


def _parse_waivers(sf: SourceFile) -> None:
    if "lint:" not in sf.src:
        return  # fast path: tokenizing every file costs ~half the gate
    # real COMMENT tokens only — a waiver spelled inside a string
    # literal (e.g. a lint test embedding fixture source) is data, not
    # a suppression
    try:
        tokens = tokenize.generate_tokens(io.StringIO(sf.src).readline)
        comments = [
            (tok.start[0], tok.string, tok.start[1])
            for tok in tokens if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return  # syntax problems are reported by the syntax pass
    lines = sf.src.splitlines()
    for lineno, text, col in comments:
        m = _WAIVER_RE.search(text)
        if not m:
            continue
        ids = tuple(p for p in re.split(r"[,\s]+", m.group("ids")) if p)
        reason = (m.group("reason") or "").strip()
        sf.waivers.append(Waiver(line=lineno, pass_ids=ids, reason=reason))
        covered = {lineno}
        line_text = lines[lineno - 1] if lineno <= len(lines) else ""
        if line_text[:col].strip() == "":
            covered.add(lineno + 1)  # standalone comment: next line too
        for ln in covered:
            sf.waived_lines.setdefault(ln, set()).update(ids)


def problem(sf: SourceFile, line: int, pass_id: str, msg: str) -> Problem:
    return Problem(sf.rel, line, msg, pass_id=pass_id)


def apply_waivers(sf: SourceFile, problems: list[Problem]) -> list[Problem]:
    """Drop findings covered by a justified per-line waiver; report
    waivers that are missing their justification."""
    out = [
        p for p in problems
        if p.pass_id not in sf.waived_lines.get(p.line, ())
    ]
    return out


def waiver_problems(sf: SourceFile, known_ids: set[str]) -> list[Problem]:
    out: list[Problem] = []
    for w in sf.waivers:
        if not w.reason:
            out.append(problem(
                sf, w.line, "waiver",
                "waiver without justification: write "
                "'# lint: ok <pass-id> — <reason>'",
            ))
        for pid in w.pass_ids:
            if pid not in known_ids:
                out.append(problem(
                    sf, w.line, "waiver",
                    f"waiver names unknown pass {pid!r} "
                    f"(known: {', '.join(sorted(known_ids))})",
                ))
    return out


def dotted_name(expr: ast.expr) -> str | None:
    """Render Name/Attribute chains: ``self._engine.step`` / ``time.sleep``.
    Calls inside the chain break it (returns None)."""
    parts: list[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        return None
    return ".".join(reversed(parts))
