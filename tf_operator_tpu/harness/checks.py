"""Static checks runner — the py_checks.py analog (lint + syntax gate).

Parity: py/py_checks.py:18 (pylint over the tree + unittest discovery as a
CI gate). The environment ships no linter, so the checks are self-contained:
per-file syntax compilation, an AST unused-import lint, and the project
passes in ``tf_operator_tpu/harness/lint/`` (lock-order, guarded-attr,
blocking-under-lock, metrics-registry, typed-error — see
docs/static-analysis.md). Unit tests are a separate workflow step (pytest),
matching the reference's split.

    python -m tf_operator_tpu.harness.checks [paths...]
    python -m tf_operator_tpu.harness.checks --list-passes
    python -m tf_operator_tpu.harness.checks --select lock-order,typed-error

Findings can be waived per line with a justified comment
(``# lint: ok <pass-id> — <reason>``); there is no blanket ignore.
"""

from __future__ import annotations

import argparse
import ast
import os
import sys
from dataclasses import dataclass

DEFAULT_PATHS = (
    "tf_operator_tpu", "tests", "examples", "tools",
    "bench.py", "perf_probe.py", "__graft_entry__.py",
)

# Directories holding deliberately-broken lint-pass fixtures (test data,
# not shipped code): excluded from the walk the same way __pycache__ is.
_FIXTURE_DIRS = {"lint_fixtures"}


@dataclass
class Problem:
    path: str
    line: int
    message: str
    pass_id: str = ""

    def __str__(self) -> str:
        tag = f" [{self.pass_id}]" if self.pass_id else ""
        return f"{self.path}:{self.line}:{tag} {self.message}"


def _py_files(paths: tuple[str, ...], root: str) -> list[str]:
    out: list[str] = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full):
            out.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [
                d for d in dirnames
                if d != "__pycache__" and d not in _FIXTURE_DIRS
            ]
            out.extend(
                os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
            )
    return sorted(out)


def check_syntax(path: str, src: str | None = None) -> list[Problem]:
    if src is None:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    try:
        compile(src, path, "exec")
    except SyntaxError as exc:
        return [Problem(path, exc.lineno or 0, f"syntax error: {exc.msg}",
                        pass_id="syntax")]
    return []


def check_unused_imports(path: str, src: str | None = None,
                         tree: ast.Module | None = None) -> list[Problem]:
    if src is None:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    if tree is None:
        try:
            tree = ast.parse(src)
        except SyntaxError:
            return []  # reported by check_syntax
    imported: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported.setdefault(name, node.lineno)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                imported.setdefault(a.asname or a.name, node.lineno)
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
    # names listed in __all__ count as used (re-export idiom) — only the
    # __all__ assignment, not arbitrary string literals, or any dict key
    # that happens to spell an import name would mask real unused imports
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        if not any(
            isinstance(t, ast.Name) and t.id == "__all__" for t in targets
        ):
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                used.add(sub.value)
    return [
        Problem(path, lineno, f"unused import: {name}",
                pass_id="unused-import")
        for name, lineno in sorted(imported.items(), key=lambda kv: kv[1])
        if name not in used
    ]


def list_passes() -> list[tuple[str, str]]:
    """(pass id, one-line doc) for every pass, generic + project."""
    from tf_operator_tpu.harness.lint import PASSES
    out = [
        ("syntax", "every .py file compiles"),
        ("unused-import", "imports are referenced (or re-exported "
                          "via __all__)"),
    ]
    out.extend((pid, doc) for pid, doc, _run in PASSES)
    return out


def run_checks(paths: tuple[str, ...] = DEFAULT_PATHS,
               root: str | None = None,
               select: tuple[str, ...] | None = None) -> list[Problem]:
    """Run the full pass set (or a ``select`` subset of pass ids) over
    ``paths``. Files are parsed once and shared by every pass; per-line
    justified waivers are the only suppression mechanism."""
    from tf_operator_tpu.harness.lint import (
        PASS_IDS, load_source_file, run_lint_passes,
    )
    root = root or os.getcwd()
    generic = {"syntax", "unused-import"}
    if select:
        unknown = set(select) - generic - set(PASS_IDS)
        if unknown:
            raise ValueError(
                f"unknown pass id(s): {sorted(unknown)}; known: "
                f"{sorted(generic) + list(PASS_IDS)}"
            )
    files = [load_source_file(p, root) for p in _py_files(paths, root)]
    problems: list[Problem] = []
    for sf in files:
        file_problems: list[Problem] = []
        if not select or "syntax" in select:
            # always compile(): a few SyntaxErrors (late __future__
            # imports, some scoping rules) pass ast.parse but fail
            # compile — ast success is NOT sufficient for this pass
            file_problems.extend(check_syntax(sf.rel, sf.src))
        if not select or "unused-import" in select:
            file_problems.extend(
                check_unused_imports(sf.rel, sf.src, sf.tree))
        problems.extend(
            p for p in file_problems
            if p.pass_id not in sf.waived_lines.get(p.line, ())
        )
    project_select = None
    if select:
        project_select = tuple(s for s in select if s in PASS_IDS)
        if not project_select:
            return problems
    problems.extend(run_lint_passes(files, select=project_select))
    return problems


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS))
    p.add_argument("--root", default=os.getcwd())
    p.add_argument("--list-passes", action="store_true",
                   help="print the pass catalog and exit")
    p.add_argument("--select", default="",
                   help="comma-separated pass ids to run (default: all)")
    args = p.parse_args(argv)
    if args.list_passes:
        for pid, doc in list_passes():
            print(f"{pid:20s} {doc}")
        return 0
    select = tuple(s for s in args.select.split(",") if s) or None
    problems = run_checks(tuple(args.paths), args.root, select=select)
    for prob in problems:
        print(prob, file=sys.stderr)
    print(f"checks: {len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
