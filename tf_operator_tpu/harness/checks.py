"""Static checks runner — the py_checks.py analog (lint + syntax gate).

Parity: py/py_checks.py:18 (pylint over the tree + unittest discovery as a
CI gate). The environment ships no linter, so the checks are self-contained:
per-file syntax compilation and an AST unused-import lint. Unit tests are a
separate workflow step (pytest), matching the reference's split.

    python -m tf_operator_tpu.harness.checks [paths...]
"""

from __future__ import annotations

import argparse
import ast
import os
import sys
from dataclasses import dataclass

DEFAULT_PATHS = ("tf_operator_tpu", "tests", "examples", "bench.py")


@dataclass
class Problem:
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.message}"


def _py_files(paths: tuple[str, ...], root: str) -> list[str]:
    out: list[str] = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full):
            out.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(
                os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
            )
    return sorted(out)


def check_syntax(path: str) -> list[Problem]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        compile(src, path, "exec")
    except SyntaxError as exc:
        return [Problem(path, exc.lineno or 0, f"syntax error: {exc.msg}")]
    return []


def check_unused_imports(path: str) -> list[Problem]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []  # reported by check_syntax
    imported: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported.setdefault(name, node.lineno)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                imported.setdefault(a.asname or a.name, node.lineno)
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
    # names listed in __all__ count as used (re-export idiom) — only the
    # __all__ assignment, not arbitrary string literals, or any dict key
    # that happens to spell an import name would mask real unused imports
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        if not any(
            isinstance(t, ast.Name) and t.id == "__all__" for t in targets
        ):
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                used.add(sub.value)
    return [
        Problem(path, lineno, f"unused import: {name}")
        for name, lineno in sorted(imported.items(), key=lambda kv: kv[1])
        if name not in used
    ]


def run_checks(paths: tuple[str, ...] = DEFAULT_PATHS,
               root: str | None = None) -> list[Problem]:
    root = root or os.getcwd()
    problems: list[Problem] = []
    for path in _py_files(paths, root):
        problems.extend(check_syntax(path))
        problems.extend(check_unused_imports(path))
    return problems


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS))
    p.add_argument("--root", default=os.getcwd())
    args = p.parse_args(argv)
    problems = run_checks(tuple(args.paths), args.root)
    for prob in problems:
        print(prob, file=sys.stderr)
    print(f"checks: {len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
