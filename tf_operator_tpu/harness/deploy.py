"""Deploy tooling: bring up / tear down an operator stack for CI and tests.

Parity: py/deploy.py (GKE cluster setup + ksonnet deploy of the operator,
`deploy.py:98,180,254`). The TPU-native framework's "cluster" is the
operator process itself (in-memory runtime + HTTP API + local executor), so
deploy == launch an operator subprocess, wait for its API to answer, and
hand back the master URL; teardown == terminate it. Used as a context
manager by the test fixtures and the E2E workflow, or standalone:

    python -m tf_operator_tpu.harness.deploy up --port 8080 --pid-file op.pid
    python -m tf_operator_tpu.harness.deploy down --pid-file op.pid
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import Any

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def host_load_factor(cap: float = 8.0) -> float:
    """Readiness-budget multiplier for contended hosts: 1-minute loadavg per
    CPU, clamped to [1, cap]. An unloaded host keeps budgets tight (failures
    surface fast); a saturated single-core CI host gets proportionally more
    time instead of flaking while the process is still making progress."""
    try:
        load = os.getloadavg()[0]
    except (OSError, AttributeError):  # not available on all platforms
        return 1.0
    return max(1.0, min(load / (os.cpu_count() or 1), cap))


class OperatorDeployment:
    """A live operator subprocess (API server + controller + executor)."""

    def __init__(
        self,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        local_executor: bool = True,
        dashboard: bool = False,
        reconcile_period: float = 0.3,
        informer_resync: float = 1.0,
        log_path: str | None = None,
        env: dict[str, str] | None = None,
        startup_timeout: float = 20.0,
        exit_with_parent: bool = True,
    ) -> None:
        self.host = host
        self.port = port or _free_port()
        self.log_path = log_path
        self._startup_timeout = startup_timeout
        self._proc: subprocess.Popen | None = None
        self._argv = [
            sys.executable, "-m", "tf_operator_tpu.cli.operator",
            "--serve", str(self.port), "--serve-host", host,
            "--reconcile-period", str(reconcile_period),
            "--informer-resync", str(informer_resync),
        ]
        if exit_with_parent:
            # A SIGKILLed harness (pytest timeout, CI reaper) must not leak
            # an operator that churns CPU forever on its orphaned state.
            # (The detached `deploy up` mode opts out — it must outlive
            # the CLI that spawned it.)
            self._argv.append("--exit-with-parent")
        if local_executor:
            self._argv.append("--local-executor")
        if dashboard:
            self._argv.append("--dashboard")
        self._env = dict(os.environ)
        self._env["PYTHONPATH"] = (
            REPO_ROOT + os.pathsep + self._env.get("PYTHONPATH", "")
        )
        if env:
            self._env.update(env)

    @property
    def master(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def pid(self) -> int | None:
        return self._proc.pid if self._proc else None

    def start(self) -> "OperatorDeployment":
        # Log to a file (undrained pipes block the operator mid-reconcile).
        out: Any = subprocess.DEVNULL
        if self.log_path:
            out = open(self.log_path, "wb")
        self._proc = subprocess.Popen(
            self._argv, env=self._env, stdout=out, stderr=subprocess.STDOUT
        )
        # Load-proportional readiness budget: a contended CI host (full
        # suite in parallel) stretches interpreter start + first reconcile
        # well past the unloaded ~2s; the observed flake was "not ready
        # after 22s" under load while the operator was still coming up.
        load_factor = host_load_factor()
        budget = self._startup_timeout * load_factor
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(self.master + "/api/tpujobs", timeout=1)
                return self
            except (urllib.error.URLError, ConnectionError):
                if self._proc.poll() is not None:
                    raise RuntimeError(
                        f"operator died at startup (rc={self._proc.returncode}"
                        f"{', log ' + self.log_path if self.log_path else ''})"
                    )
                time.sleep(0.2)
        self.stop()
        raise TimeoutError(
            f"operator API not ready on {self.master} after "
            f"{budget:.0f}s (load factor {load_factor:.1f})"
        )

    def stop(self, grace: float = 5.0) -> None:
        if self._proc is None:
            return
        self._proc.terminate()
        try:
            self._proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait(timeout=grace)
        self._proc = None

    def __enter__(self) -> "OperatorDeployment":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def kubectl_deploy(
    action: str,
    *,
    kubeconfig: str | None = None,
    context: str | None = None,
    namespace: str = "tpu-operator-system",
    image: str | None = None,
    bundle: str | None = None,
    runner=subprocess.run,
) -> list[list[str]]:
    """Apply/delete the CRD + operator manifests on a real cluster.

    Parity: py/deploy.py:180 (ksonnet apply of the operator onto GKE) —
    here plain `kubectl apply -f` of deploy/crd.yaml + deploy/operator.yaml,
    with the Deployment's image pinned to the release tag (manifest.json
    "image_tag"), or of a versioned release bundle's rendered templates
    when ``bundle`` (a release/bundle.py tarball) is given. Returns the
    kubectl argvs it ran; ``runner`` is injectable so tests can record
    instead of execute.
    """
    if action not in ("apply", "delete"):
        raise ValueError(f"action must be apply|delete, not {action!r}")
    base = ["kubectl"]
    if kubeconfig:
        base += ["--kubeconfig", kubeconfig]
    if context:
        base += ["--context", context]
    deploy_dir = os.path.join(REPO_ROOT, "deploy")
    crd = os.path.join(deploy_dir, "crd.yaml")
    crd_doc: bytes | None = None
    operator_doc: bytes
    if bundle:
        # Versioned bundle (release/bundle.py, helm-chart analog): both
        # manifests come from the bundle's templates with values
        # substituted — the repo's deploy/ dir is not consulted, so a
        # pinned release deploys the same bits on any checkout.
        from tf_operator_tpu.release.bundle import load_bundle, render

        overrides: dict[str, Any] = {"namespace": namespace}
        if image:
            overrides["image"] = image
        docs = render(load_bundle(bundle), overrides)
        crd_doc = docs["crd.yaml"].encode()
        operator_doc = docs["operator.yaml"].encode()
    else:
        operator_doc = _render_operator_manifest(namespace, image).encode()
    ran: list[list[str]] = []

    def run(cmd: list[str], **kw: Any) -> None:
        ran.append(cmd)
        result = runner(cmd, **kw)
        rc = getattr(result, "returncode", 0)
        if rc not in (0, None):
            raise RuntimeError(f"{' '.join(cmd)} failed with rc={rc}")

    def probe(cmd: list[str]) -> bool:
        """Run without raising; True when the command succeeded."""
        ran.append(cmd)
        result = runner(cmd, capture_output=True)
        return getattr(result, "returncode", 0) in (0, None)

    # operator.yaml pins its objects' namespaces in-document (the
    # ClusterRoleBinding subject needs one regardless), so a custom
    # namespace — and the image tag — are templated into the doc and
    # shipped over stdin: never `-f file -n ns` (kubectl rejects the
    # namespace mismatch), and never apply-then-`set image` (the apply
    # would transiently roll the Deployment back to the placeholder tag).
    ignore = ["--ignore-not-found"] if action == "delete" else []

    def run_crd(verb: list[str]) -> None:
        if crd_doc is not None:
            run(base + verb + ["-f", "-"], input=crd_doc)
        else:
            run(base + verb + ["-f", crd])

    if action == "apply":
        # Namespace first (idempotent), CRD before the operator watches it.
        run(base + ["apply", "-f", "-"], input=_namespace_yaml(namespace).encode())
        # API write-auth token: generated randomly per cluster on first
        # deploy, NEVER rotated on re-apply (the operator reads it at
        # startup; silent rotation would strand running clients). The token
        # travels over stdin — argv would leak it to `ps` and error logs.
        get_secret = base + ["-n", namespace, "get", "secret",
                             "tpu-operator-api-token"]
        if not probe(get_secret):
            import secrets as _secrets

            create_cmd = base + ["-n", namespace, "create", "secret",
                                 "generic", "tpu-operator-api-token",
                                 "--from-file=token=/dev/stdin"]
            try:
                run(create_cmd, input=_secrets.token_hex(24).encode())
            except RuntimeError:
                # Lost a create race (or the earlier probe false-negatived
                # on a transient error): fine as long as the secret exists.
                if not probe(get_secret):
                    raise
        run_crd(["apply"])
        run(base + ["apply", "-f", "-"], input=operator_doc)
    else:
        # Reverse order: stop the operator before removing its CRD.
        run(base + ["delete", "-f", "-"] + ignore, input=operator_doc)
        run_crd(["delete"] + ignore)
    return ran


# ---------------------------------------------------------------------------
# GKE TPU cluster provisioning (py/deploy.py:98,180,254 parity)
# ---------------------------------------------------------------------------

# GKE machine-type family per TPU generation; the suffix is the host's chip
# count (cloud.google.com/tpu docs: ct5lp-hightpu-{1,4,8}t etc.).
_GKE_MACHINE_PREFIX = {
    "v4": "ct4p-hightpu",
    "v5e": "ct5lp-hightpu",
    "v5p": "ct5p-hightpu",
    "v6e": "ct6e-standard",
}


def gke_machine_type(generation: str, chips_per_host: int) -> str:
    try:
        prefix = _GKE_MACHINE_PREFIX[generation]
    except KeyError:
        raise ValueError(
            f"no GKE machine-type mapping for TPU generation {generation!r}"
        ) from None
    return f"{prefix}-{chips_per_host}t"


class GKEProvisioner:
    """Creates/tears down a GKE cluster with TPU slice node pools.

    Parity: the reference harness provisions GKE clusters for CI
    (py/deploy.py:98 setup_cluster, :180 deploy via ksonnet, :254
    teardown); this is the same lifecycle with TPU node pools instead of
    GPU ones. The exact gcloud command sequence is a first-class output
    (``up_commands``/``down_commands``) so ``--dry-run`` CI and tests can
    assert it without a cloud project; execution just runs that sequence
    through the injectable ``runner``.

    Shape rules (GKE TPU conventions): one node pool per slice; a pool's
    node count equals the slice's host count; multi-host pools carry
    ``--tpu-topology``. The default CPU pool (one e2 node) hosts the
    operator Deployment itself.
    """

    def __init__(
        self,
        name: str,
        project: str,
        zone: str,
        *,
        accelerator_type: str = "v5e-16",
        topology: str | None = None,
        num_slices: int = 1,
        spot: bool = False,
        runner=subprocess.run,
    ) -> None:
        from tf_operator_tpu.topology import slices as slices_mod

        self.name = name
        self.project = project
        self.zone = zone
        self.num_slices = num_slices
        self.spot = spot
        self.slice_topology = slices_mod.resolve(accelerator_type, topology)
        self._runner = runner

    def _gcloud(self, *args: str) -> list[str]:
        return [
            "gcloud", "container", *args,
            "--project", self.project, "--zone", self.zone, "--quiet",
        ]

    def up_commands(self) -> list[list[str]]:
        st = self.slice_topology
        cmds = [
            self._gcloud(
                "clusters", "create", self.name,
                "--release-channel", "regular",
                "--num-nodes", "1",
                "--machine-type", "e2-standard-4",
            )
        ]
        for i in range(self.num_slices):
            pool = self._gcloud(
                "node-pools", "create", f"tpu-slice-{i}",
                "--cluster", self.name,
                "--machine-type",
                gke_machine_type(st.generation, st.chips_per_host),
                "--num-nodes", str(st.num_hosts),
            )
            if st.multi_host:
                pool += ["--tpu-topology", st.topology]
            if self.spot:
                pool += ["--spot"]
            cmds.append(pool)
        cmds.append(
            self._gcloud("clusters", "get-credentials", self.name)
        )
        return cmds

    def down_commands(self) -> list[list[str]]:
        # Deleting the cluster reclaims its node pools; no per-pool delete
        # needed (and TPU capacity frees fastest this way).
        return [self._gcloud("clusters", "delete", self.name)]

    def _run(self, cmds: list[list[str]], dry_run: bool) -> list[list[str]]:
        for cmd in cmds:
            if dry_run:
                print(" ".join(cmd))
                continue
            result = self._runner(cmd)
            rc = getattr(result, "returncode", 0)
            if rc not in (0, None):
                raise RuntimeError(f"{' '.join(cmd)} failed with rc={rc}")
        return cmds

    def up(self, *, dry_run: bool = False) -> list[list[str]]:
        return self._run(self.up_commands(), dry_run)

    def down(self, *, dry_run: bool = False) -> list[list[str]]:
        return self._run(self.down_commands(), dry_run)


def _namespace_yaml(namespace: str) -> str:
    return f"apiVersion: v1\nkind: Namespace\nmetadata:\n  name: {namespace}\n"


def _render_operator_manifest(namespace: str, image: str | None = None) -> str:
    """deploy/operator.yaml with pinned namespaces re-targeted and the
    placeholder image replaced by the release tag (manifest.json
    image_tag) when given."""
    with open(os.path.join(REPO_ROOT, "deploy", "operator.yaml")) as f:
        doc = f.read()
    doc = doc.replace("namespace: default", f"namespace: {namespace}")
    if image:
        doc = doc.replace("image: tpu-operator:latest", f"image: {image}")
    return doc


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    up = sub.add_parser("up")
    up.add_argument("--port", type=int, default=0)
    up.add_argument("--pid-file", required=True)
    up.add_argument("--log-file", default=None)
    up.add_argument("--dashboard", action="store_true")
    down = sub.add_parser("down")
    down.add_argument("--pid-file", required=True)
    for name in ("kube-up", "kube-down"):
        k = sub.add_parser(name, help="apply/delete CRD + operator on a cluster")
        k.add_argument("--kubeconfig", default=None)
        k.add_argument("--kube-context", default=None)
        k.add_argument("--namespace", default="tpu-operator-system")
        k.add_argument("--image", default=None,
                       help="operator image tag (manifest.json image_tag)")
        k.add_argument("--bundle", default=None, metavar="TAR_GZ",
                       help="deploy from a versioned release bundle "
                            "(manifest.json \"bundle\") instead of the "
                            "repo's deploy/ manifests")
        k.add_argument("--echo", action="store_true",
                       help="print kubectl commands instead of running them")
    for name in ("cluster-up", "cluster-down"):
        c = sub.add_parser(
            name, help="provision/tear down a GKE cluster with TPU node pools"
        )
        c.add_argument("--name", default="tpu-operator-e2e")
        c.add_argument("--project", required=True)
        c.add_argument("--zone", required=True)
        c.add_argument("--accelerator-type", default="v5e-16")
        c.add_argument("--topology", default=None,
                       help="explicit slice topology (e.g. 4x4); inferred "
                            "from --accelerator-type when omitted")
        c.add_argument("--num-slices", type=int, default=1)
        c.add_argument("--spot", action="store_true")
        c.add_argument("--dry-run", action="store_true",
                       help="print the exact gcloud command sequence "
                            "instead of running it")
    args = p.parse_args(argv)

    if args.cmd in ("cluster-up", "cluster-down"):
        prov = GKEProvisioner(
            args.name, args.project, args.zone,
            accelerator_type=args.accelerator_type,
            topology=args.topology,
            num_slices=args.num_slices,
            spot=args.spot,
        )
        if args.cmd == "cluster-up":
            prov.up(dry_run=args.dry_run)
        else:
            prov.down(dry_run=args.dry_run)
        return 0

    if args.cmd in ("kube-up", "kube-down"):
        runner: Any = subprocess.run
        if args.echo:
            class _Echo:
                returncode = 0
            runner = lambda cmd, **kw: (print(" ".join(cmd)), _Echo())[1]  # noqa: E731
        kubectl_deploy(
            "apply" if args.cmd == "kube-up" else "delete",
            kubeconfig=args.kubeconfig, context=args.kube_context,
            namespace=args.namespace, image=args.image,
            bundle=args.bundle, runner=runner,
        )
        return 0

    if args.cmd == "up":
        dep = OperatorDeployment(
            port=args.port, dashboard=args.dashboard, log_path=args.log_file,
            exit_with_parent=False,  # detached: must outlive this CLI
        )
        dep.start()
        with open(args.pid_file, "w") as f:
            f.write(f"{dep.pid}\n{dep.master}\n")
        print(dep.master)
        # Detach: the subprocess outlives this CLI.
        dep._proc = None  # noqa: SLF001 — intentional detach
        return 0
    pid = int(open(args.pid_file).read().splitlines()[0])
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        pass
    os.unlink(args.pid_file)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
