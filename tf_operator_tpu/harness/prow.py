"""CI artifact metadata: started.json / finished.json.

Parity: py/prow.py:81-119 (create_started / create_finished) — the contract
Prow-style CI dashboards read from the artifact directory to render run
status. Kept format-compatible: epoch timestamps, pull/repo metadata in
started.json, success/result plus metadata in finished.json.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Any


def git_sha(repo_root: str | None = None) -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=repo_root, capture_output=True, text=True, timeout=10,
        )
        if out.returncode == 0:
            return out.stdout.strip()
    except OSError:
        pass
    return "unknown"


def create_started(artifacts_dir: str, *, repo: str = "", pull: str = "",
                   repo_root: str | None = None,
                   now: float | None = None) -> dict[str, Any]:
    started = {
        "timestamp": int(now if now is not None else time.time()),
        "repos": {repo: pull} if repo else {},
        "repo-version": git_sha(repo_root),
    }
    os.makedirs(artifacts_dir, exist_ok=True)
    with open(os.path.join(artifacts_dir, "started.json"), "w") as f:
        json.dump(started, f, indent=2, sort_keys=True)
    return started


def create_finished(artifacts_dir: str, success: bool,
                    metadata: dict[str, Any] | None = None,
                    *, now: float | None = None) -> dict[str, Any]:
    finished = {
        "timestamp": int(now if now is not None else time.time()),
        "result": "SUCCESS" if success else "FAILURE",
        "passed": bool(success),
        "metadata": metadata or {},
    }
    os.makedirs(artifacts_dir, exist_ok=True)
    with open(os.path.join(artifacts_dir, "finished.json"), "w") as f:
        json.dump(finished, f, indent=2, sort_keys=True)
    return finished
