"""Versioned deploy bundle — the helm-chart analog.

Parity: /root/reference/py/release.py:54-70 (the reference releases a
versioned helm chart next to the image, with chart/values versions
rewritten per release). This framework's equivalent is a deterministic
tarball:

    tpu-operator-bundle-{tag}/
      bundle.json        # name/version/git_sha/created metadata
      values.json        # default values (namespace, image, replicas,
                         #   resources, leader election)
      templates/crd.yaml
      templates/operator.yaml   # {{key}} placeholders for every value

``render()`` substitutes values (defaults overlaid with caller
overrides) into the templates, strictly: an unknown override key and an
unsubstituted placeholder are both errors, so a template/values drift
cannot ship silently. `deploy.py kube-up --bundle` consumes the tarball
directly; the round-trip is pinned by
tests/test_ci_tooling.py::test_bundle_roundtrip_build_render_deploy.

The templates are derived mechanically from deploy/crd.yaml +
deploy/operator.yaml at build time (single source of truth — the bundle
can never drift from what `kubectl apply -f deploy/` installs).
"""

from __future__ import annotations

import io
import json
import os
import re
import tarfile
from typing import Any

# Literal -> placeholder rewrites applied to deploy/operator.yaml. Each
# pattern must hit at least once or the build fails (guards against the
# source manifest drifting away from the parameterization).
_TEMPLATE_REWRITES: tuple[tuple[str, str], ...] = (
    (r"namespace: default\b", "namespace: {{namespace}}"),
    # [^\S\n]* (horizontal whitespace only): with plain \s* the match
    # could cross the newline when the inline comment is absent and
    # swallow the next line's indentation, producing invalid YAML while
    # the must-match-once guard still passes.
    (r"image: tpu-operator:latest[^\S\n]*(#[^\n]*)?", "image: {{image}}"),
    (r"replicas: 1\b", "replicas: {{replicas}}"),
    (r"requests: \{cpu: 100m, memory: 256Mi\}",
     "requests: {cpu: {{cpu_request}}, memory: {{memory_request}}}"),
    (r"limits: \{cpu: \"1\", memory: 1Gi\}",
     "limits: {cpu: {{cpu_limit}}, memory: {{memory_limit}}}"),
)

DEFAULT_VALUES: dict[str, Any] = {
    "namespace": "tpu-operator-system",
    "image": "tpu-operator:latest",
    "replicas": 1,
    "cpu_request": "100m",
    "memory_request": "256Mi",
    "cpu_limit": '"1"',
    "memory_limit": "1Gi",
}

_PLACEHOLDER = re.compile(r"\{\{(\w+)\}\}")


def _operator_template(repo_root: str) -> str:
    with open(os.path.join(repo_root, "deploy", "operator.yaml")) as f:
        doc = f.read()
    for pattern, repl in _TEMPLATE_REWRITES:
        doc, n = re.subn(pattern, repl, doc)
        if n == 0:
            raise RuntimeError(
                f"deploy/operator.yaml no longer matches bundle "
                f"parameterization {pattern!r} — update _TEMPLATE_REWRITES"
            )
    return doc


def build_bundle(
    repo_root: str, out_dir: str, *, name_tag: str, version: str,
    git_sha: str, image: str | None = None,
) -> dict[str, Any]:
    """Write tpu-operator-bundle-{name_tag}.tar.gz into out_dir.

    ``image``: the release's digest-pinned ref (or tag) baked in as the
    default image value, so `render(bundle)` with no overrides deploys
    exactly the bits this release built.
    """
    bundle_name = f"tpu-operator-bundle-{name_tag}"
    values = dict(DEFAULT_VALUES)
    if image:
        values["image"] = image
    meta = {
        "name": bundle_name,
        "version": version,
        "git_sha": git_sha,
        "values_schema": sorted(values),
    }
    with open(os.path.join(repo_root, "deploy", "crd.yaml")) as f:
        crd = f.read()
    members = {
        f"{bundle_name}/bundle.json": json.dumps(
            meta, indent=2, sort_keys=True),
        f"{bundle_name}/values.json": json.dumps(
            values, indent=2, sort_keys=True),
        f"{bundle_name}/templates/crd.yaml": crd,
        f"{bundle_name}/templates/operator.yaml": _operator_template(
            repo_root),
    }
    os.makedirs(out_dir, exist_ok=True)
    tar_path = os.path.join(out_dir, f"{bundle_name}.tar.gz")
    # Deterministic: fixed mtime/uid/gid, sorted members, pinned gzip
    # header — one shared contract with build_release's source tarball.
    from tf_operator_tpu.release.build import open_deterministic_targz

    with open_deterministic_targz(tar_path) as tar:
        for arcname in sorted(members):
            data = members[arcname].encode()
            info = tarfile.TarInfo(arcname)
            info.size = len(data)
            info.mode = 0o644
            tar.addfile(info, io.BytesIO(data))
    return {
        "bundle": os.path.basename(tar_path),
        "bundle_name": bundle_name,
        "bundle_values": values,
    }


def load_bundle(tar_path: str) -> dict[str, Any]:
    """Read a bundle tarball -> {meta, values, templates: {filename: doc}}."""
    out: dict[str, Any] = {"templates": {}}
    with tarfile.open(tar_path, "r:gz") as tar:
        for member in tar.getmembers():
            if not member.isfile():  # dir entries from repacked tarballs
                continue
            rel = member.name.split("/", 1)[1] if "/" in member.name else member.name
            data = tar.extractfile(member).read().decode()
            if rel == "bundle.json":
                out["meta"] = json.loads(data)
            elif rel == "values.json":
                out["values"] = json.loads(data)
            elif rel.startswith("templates/"):
                out["templates"][rel.removeprefix("templates/")] = data
    for key in ("meta", "values"):
        if key not in out:
            raise ValueError(f"bundle {tar_path}: missing {key}.json")
    if not out["templates"]:
        raise ValueError(f"bundle {tar_path}: no templates/")
    return out


def render(
    bundle: dict[str, Any], overrides: dict[str, Any] | None = None,
) -> dict[str, str]:
    """Substitute values (defaults overlaid with overrides) into every
    template; returns {filename: rendered doc}. Strict both ways."""
    values = dict(bundle["values"])
    for key, val in (overrides or {}).items():
        if key not in values:
            raise ValueError(
                f"unknown value {key!r}; bundle accepts {sorted(values)}"
            )
        values[key] = val
    rendered: dict[str, str] = {}
    for fname, doc in bundle["templates"].items():
        def sub(match: re.Match) -> str:
            key = match.group(1)
            if key not in values:
                raise ValueError(
                    f"{fname}: template references undeclared value {key!r}"
                )
            return str(values[key])

        rendered[fname] = _PLACEHOLDER.sub(sub, doc)
    return rendered
