"""Release builder: versioned, git-tagged framework artifacts.

Parity: py/release.py + py/build_and_push_image.py (build the operator
binaries + dashboard into one image, tag by git hash, write a manifest the
deploy tooling consumes). The TPU-native framework is pure Python + JAX, so
the artifact is a tarball of the package tree (sources + dashboard frontend
+ examples) with a manifest.json carrying version/git-sha/content digest —
the same contract (content-addressed, reproducibly tagged) without a Docker
daemon in the loop.

CLI:  python -m tf_operator_tpu.release.build --out dist/
"""

from __future__ import annotations

import argparse
import contextlib
import gzip
import hashlib
import io
import json
import os
import shutil
import tarfile
import time
from typing import Any


@contextlib.contextmanager
def open_deterministic_targz(path: str):
    """tarfile writer whose output is byte-identical across rebuilds.

    Plain ``tarfile.open(path, "w:gz")`` stamps the wall clock into the
    gzip HEADER (byte 4), so two otherwise-identical builds crossing a
    second boundary differ; an explicit GzipFile(mtime=0) pins it. ONE
    copy of this contract — the source tarball and the deploy bundle
    both write through it (member mtimes/owners are the caller's job)."""
    with open(path, "wb") as raw, gzip.GzipFile(
        fileobj=raw, mode="wb", mtime=0
    ) as gz, tarfile.open(fileobj=gz, mode="w") as tar:
        yield tar

from tf_operator_tpu import version as version_mod
from tf_operator_tpu.harness.prow import git_sha

# _build holds machine-compiled .so files (content varies by host/arch and
# by whether a compile has run) — shipping them would break both the
# reproducible content digest and portability; targets rebuild on demand.
EXCLUDE_DIRS = {"__pycache__", ".git", ".pytest_cache", "dist", "_build"}
INCLUDE_TOP = (
    "tf_operator_tpu", "examples", "bench.py", "README.md", "pyproject.toml",
)


def _walk_files(repo_root: str) -> list[str]:
    files: list[str] = []
    for top in INCLUDE_TOP:
        path = os.path.join(repo_root, top)
        if os.path.isfile(path):
            files.append(top)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames if d not in EXCLUDE_DIRS)
            for fn in sorted(filenames):
                if fn.endswith((".pyc", ".pyo")):
                    continue
                full = os.path.join(dirpath, fn)
                files.append(os.path.relpath(full, repo_root))
    return sorted(files)


def content_digest(repo_root: str, files: list[str]) -> str:
    """Deterministic digest over relative paths + file bytes."""
    h = hashlib.sha256()
    for rel in files:
        h.update(rel.encode())
        h.update(b"\0")
        with open(os.path.join(repo_root, rel), "rb") as f:
            for chunk in iter(lambda: f.read(1 << 16), b""):
                h.update(chunk)
        h.update(b"\0")
    return h.hexdigest()


def build_release(repo_root: str, out_dir: str,
                  *, version: str | None = None) -> dict[str, Any]:
    """Write {name}.tar.gz + manifest.json into out_dir; returns manifest."""
    version = version or version_mod.VERSION
    sha = git_sha(repo_root)
    files = _walk_files(repo_root)
    digest = content_digest(repo_root, files)
    tag = f"{version}-g{sha[:12]}" if sha != "unknown" else version
    name = f"tpu-operator-{tag}"

    os.makedirs(out_dir, exist_ok=True)
    tar_path = os.path.join(out_dir, f"{name}.tar.gz")
    # Deterministic tar: fixed mtime/uid/gid, sorted members; the gzip
    # header is pinned by open_deterministic_targz.
    with open_deterministic_targz(tar_path) as tar:
        for rel in files:
            full = os.path.join(repo_root, rel)
            info = tar.gettarinfo(full, arcname=f"{name}/{rel}")
            info.uid = info.gid = 0
            info.uname = info.gname = ""
            info.mtime = 0
            with open(full, "rb") as f:
                tar.addfile(info, io.BytesIO(f.read()))

    manifest = {
        "name": name,
        "version": version,
        "git_sha": sha,
        "content_digest": digest,
        "artifact": os.path.basename(tar_path),
        "files": len(files),
        "built_at": int(time.time()),
    }
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    return manifest


def build_image_context(repo_root: str, out_dir: str,
                        manifest: dict[str, Any]) -> str:
    """Assemble a docker build dir: Dockerfile + context/ from the tarball.

    Parity: py/build_and_push_image.py stages sources next to the
    Dockerfile before `docker build`. The image tag to use is
    "tpu-operator:{git_sha}" (manifest["git_sha"]); building/pushing is
    left to the CI host's docker daemon.
    """
    image_dir = os.path.join(out_dir, "image")
    # Fresh staging dir every build: a re-run must not fail on the previous
    # context nor let files deleted from the repo survive into the image.
    shutil.rmtree(image_dir, ignore_errors=True)
    ctx = os.path.join(image_dir, "context")
    os.makedirs(ctx)
    tar_path = os.path.join(out_dir, manifest["artifact"])
    with tarfile.open(tar_path, "r:gz") as tar:
        tar.extractall(ctx, filter="data")
    # The tarball nests everything under {name}/ — flatten one level so the
    # Dockerfile's COPY context/... paths are stable across versions.
    nested = os.path.join(ctx, manifest["name"])
    for entry in os.listdir(nested):
        os.replace(os.path.join(nested, entry), os.path.join(ctx, entry))
    os.rmdir(nested)
    shutil.copyfile(
        os.path.join(repo_root, "build", "Dockerfile"),
        os.path.join(image_dir, "Dockerfile"),
    )
    return image_dir


def release_image(
    repo_root: str,
    out_dir: str,
    manifest: dict[str, Any],
    *,
    registry: str | None = None,
    repository: str = "tpu-operator",
    oci_layout: bool = False,
    token: str | None = None,
) -> dict[str, Any]:
    """Build the OCI image from the staged context and publish it.

    Tags: the release tag ({version}-g{sha12}), the full git sha, and
    "latest" — the reference's tagging scheme (release.py:123,249 tags by
    git hash; latest rides along for dev clusters). The returned block's
    digest-pinned ``ref`` is what deploy/operator.yaml templating should
    consume in production (immutable), via `deploy kube-up --image`.
    """
    from tf_operator_tpu.release import oci

    image_dir = manifest.get("image_dir") or build_image_context(
        repo_root, out_dir, manifest
    )
    image = oci.build_image(
        os.path.join(image_dir, "context"),
        labels={
            "org.opencontainers.image.revision": manifest["git_sha"],
            "org.opencontainers.image.version": manifest["version"],
            "io.tpuflow.content-digest": manifest["content_digest"],
        },
    )
    tags = [manifest["name"].removeprefix("tpu-operator-")]
    if manifest["git_sha"] != "unknown":
        tags.append(manifest["git_sha"])
    tags.append("latest")
    out: dict[str, Any] = {
        "image_digest": image.manifest_digest,
        "image_tags": tags,
    }
    if oci_layout:
        layout = os.path.join(out_dir, "oci-layout")
        oci.write_oci_layout(image, layout, tags)
        out["oci_layout"] = layout
    if registry:
        out["push"] = oci.push_image(
            image, registry, repository, tags, token=token
        )
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--repo-root", default=os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    p.add_argument("--out", default="dist")
    p.add_argument("--version", default=None)
    p.add_argument("--image-context", action="store_true",
                   help="also stage a docker build dir (Dockerfile + context)")
    p.add_argument("--registry", default=None, metavar="URL",
                   help="push the OCI image here (Registry API v2, e.g. "
                        "http://127.0.0.1:5000); implies --image-context")
    p.add_argument("--repository", default="tpu-operator",
                   help="registry repository for --registry")
    p.add_argument("--registry-token", default=None,
                   help="bearer token for --registry")
    p.add_argument("--oci-layout", action="store_true",
                   help="write a filesystem OCI image layout into OUT/"
                        "oci-layout (no registry needed); implies "
                        "--image-context")
    args = p.parse_args(argv)
    manifest = build_release(args.repo_root, args.out, version=args.version)
    wants_image = bool(args.image_context or args.registry or args.oci_layout)
    if wants_image:
        manifest["image_dir"] = build_image_context(
            args.repo_root, args.out, manifest
        )
        # Full sha: must match the documented `docker build -t` recipe
        # exactly, or the deploy-time image pin points at a never-built tag.
        manifest["image_tag"] = f"tpu-operator:{manifest['git_sha']}"
    if args.registry or args.oci_layout:
        manifest.update(
            release_image(
                args.repo_root, args.out, manifest,
                registry=args.registry,
                repository=args.repository,
                oci_layout=args.oci_layout,
                token=args.registry_token,
            )
        )
    # Versioned deploy bundle (helm-chart analog, py/release.py:54-70):
    # emitted unconditionally next to the image artifacts, with the
    # release's most-pinned image ref baked in as the default value
    # (digest-pinned push ref > local image tag > floating latest).
    from tf_operator_tpu.release.bundle import build_bundle

    image_ref = (
        (manifest.get("push") or {}).get("ref")
        or manifest.get("image_tag")
    )
    manifest.update(build_bundle(
        args.repo_root, args.out,
        name_tag=manifest["name"].removeprefix("tpu-operator-"),
        version=manifest["version"], git_sha=manifest["git_sha"],
        image=image_ref,
    ))
    # Re-write manifest.json so the on-disk manifest (what deploy tooling
    # consumes) carries the image + bundle fields, not just stdout.
    with open(os.path.join(args.out, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    print(json.dumps(manifest, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
