"""OCI image build + push, pure Python — the release pipeline's image leg.

Parity: py/build_and_push_image.py + py/release.py:123,249 (build the
operator image, tag it with the git hash, push to a registry the deploy
manifests consume). The reference shells out to `docker build` and `gcloud
docker -- push`; here the image is assembled directly — a deterministic
single-layer OCI image from the staged build context — and pushed over the
Registry HTTP API v2 (or written to a filesystem OCI layout), so releases
need no Docker daemon and are reproducible byte-for-byte from the release
tarball's content digest.

The image mirrors build/Dockerfile's runtime contract (WORKDIR/ENV/
ENTRYPOINT/CMD/EXPOSE) minus the apt layer: the context tree lands under
/opt/tpu-operator and the operator CLI is the entrypoint.
"""

from __future__ import annotations

import gzip
import hashlib
import io
import json
import os
import tarfile
from dataclasses import dataclass, field
from typing import Any
from urllib import error as urlerror
from urllib import request as urlrequest

MANIFEST_MEDIA_TYPE = "application/vnd.oci.image.manifest.v1+json"
CONFIG_MEDIA_TYPE = "application/vnd.oci.image.config.v1+json"
LAYER_MEDIA_TYPE = "application/vnd.oci.image.layer.v1.tar+gzip"

# Runtime contract copied from build/Dockerfile (kept in lockstep by
# tests/test_harness.py's release tests).
DEFAULT_PREFIX = "/opt/tpu-operator"
DEFAULT_ENTRYPOINT = ["python", "-m", "tf_operator_tpu.cli.operator"]
DEFAULT_CMD = [
    "--serve", "8080", "--serve-host", "0.0.0.0", "--backend", "kube",
    "--dashboard", "--leader-elect",
]


def digest_of(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


@dataclass
class OciImage:
    """A fully-assembled single-layer image: blobs + their digests."""

    layer: bytes  # gzipped tar
    layer_digest: str
    diff_id: str  # digest of the UNCOMPRESSED tar (rootfs.diff_ids entry)
    config: bytes
    config_digest: str
    manifest: bytes
    manifest_digest: str
    annotations: dict[str, str] = field(default_factory=dict)

    @property
    def blobs(self) -> dict[str, bytes]:
        return {
            self.layer_digest: self.layer,
            self.config_digest: self.config,
            self.manifest_digest: self.manifest,
        }


def _deterministic_layer(context_dir: str, prefix: str) -> tuple[bytes, str]:
    """(gzipped layer bytes, diff_id). Deterministic: sorted members, zeroed
    times/owners, gzip mtime 0 — same context tree → same digests."""
    raw = io.BytesIO()
    with tarfile.open(fileobj=raw, mode="w", format=tarfile.PAX_FORMAT) as tar:
        # Parent directories of the prefix, root-owned.
        parts = [p for p in prefix.strip("/").split("/") if p]
        for i in range(1, len(parts) + 1):
            info = tarfile.TarInfo("/".join(parts[:i]))
            info.type = tarfile.DIRTYPE
            info.mode = 0o755
            tar.addfile(info)
        entries: list[tuple[str, str]] = []
        for dirpath, dirnames, filenames in os.walk(context_dir):
            dirnames.sort()
            for d in dirnames:
                full = os.path.join(dirpath, d)
                entries.append((full, os.path.relpath(full, context_dir)))
            for f in sorted(filenames):
                full = os.path.join(dirpath, f)
                entries.append((full, os.path.relpath(full, context_dir)))
        for full, rel in sorted(entries, key=lambda e: e[1]):
            arcname = f"{prefix.strip('/')}/{rel}"
            info = tar.gettarinfo(full, arcname=arcname)
            info.uid = info.gid = 0
            info.uname = info.gname = ""
            info.mtime = 0
            if info.isreg():
                with open(full, "rb") as fh:
                    tar.addfile(info, fh)
            else:
                tar.addfile(info)
    tar_bytes = raw.getvalue()
    diff_id = digest_of(tar_bytes)
    zbuf = io.BytesIO()
    with gzip.GzipFile(fileobj=zbuf, mode="wb", mtime=0) as gz:
        gz.write(tar_bytes)
    return zbuf.getvalue(), diff_id


def build_image(
    context_dir: str,
    *,
    prefix: str = DEFAULT_PREFIX,
    entrypoint: list[str] | None = None,
    cmd: list[str] | None = None,
    env: list[str] | None = None,
    labels: dict[str, str] | None = None,
) -> OciImage:
    """Assemble the OCI image for a staged build context directory."""
    layer, diff_id = _deterministic_layer(context_dir, prefix)
    layer_digest = digest_of(layer)
    config_doc: dict[str, Any] = {
        "architecture": "amd64",
        "os": "linux",
        # Epoch creation time, like the zeroed tar mtimes: reproducibility
        # beats wall-clock provenance (the git sha carries provenance).
        "created": "1970-01-01T00:00:00Z",
        "config": {
            "Entrypoint": entrypoint or list(DEFAULT_ENTRYPOINT),
            "Cmd": cmd or list(DEFAULT_CMD),
            "Env": env or [f"PYTHONPATH={prefix}"],
            "WorkingDir": prefix,
            "ExposedPorts": {"8080/tcp": {}},
            "Labels": labels or {},
        },
        "rootfs": {"type": "layers", "diff_ids": [diff_id]},
        "history": [
            {
                "created": "1970-01-01T00:00:00Z",
                "created_by": "tf_operator_tpu.release.oci build_image",
            }
        ],
    }
    config = json.dumps(config_doc, sort_keys=True).encode()
    config_digest = digest_of(config)
    manifest_doc = {
        "schemaVersion": 2,
        "mediaType": MANIFEST_MEDIA_TYPE,
        "config": {
            "mediaType": CONFIG_MEDIA_TYPE,
            "digest": config_digest,
            "size": len(config),
        },
        "layers": [
            {
                "mediaType": LAYER_MEDIA_TYPE,
                "digest": layer_digest,
                "size": len(layer),
            }
        ],
        "annotations": labels or {},
    }
    manifest = json.dumps(manifest_doc, sort_keys=True).encode()
    return OciImage(
        layer=layer,
        layer_digest=layer_digest,
        diff_id=diff_id,
        config=config,
        config_digest=config_digest,
        manifest=manifest,
        manifest_digest=digest_of(manifest),
        annotations=dict(labels or {}),
    )


# ---------------------------------------------------------------------------
# Filesystem OCI layout (image-spec image-layout: usable by skopeo/crane/
# podman without any registry)
# ---------------------------------------------------------------------------

def write_oci_layout(image: OciImage, out_dir: str, tags: list[str]) -> str:
    blobs = os.path.join(out_dir, "blobs", "sha256")
    os.makedirs(blobs, exist_ok=True)
    for dig, data in image.blobs.items():
        with open(os.path.join(blobs, dig.split(":", 1)[1]), "wb") as f:
            f.write(data)
    with open(os.path.join(out_dir, "oci-layout"), "w") as f:
        json.dump({"imageLayoutVersion": "1.0.0"}, f)
    index = {
        "schemaVersion": 2,
        "manifests": [
            {
                "mediaType": MANIFEST_MEDIA_TYPE,
                "digest": image.manifest_digest,
                "size": len(image.manifest),
                "annotations": {"org.opencontainers.image.ref.name": tag},
            }
            for tag in tags
        ],
    }
    with open(os.path.join(out_dir, "index.json"), "w") as f:
        json.dump(index, f, indent=2, sort_keys=True)
    return out_dir


# ---------------------------------------------------------------------------
# Registry HTTP API v2 push
# ---------------------------------------------------------------------------

class RegistryError(Exception):
    pass


class RegistryClient:
    """Minimal Registry V2 client: blob existence check, monolithic upload,
    manifest put/get. ``base`` e.g. "http://127.0.0.1:5000" or
    "https://gcr.io"; ``token`` an optional bearer token."""

    def __init__(self, base: str, token: str | None = None, timeout: float = 60.0):
        self.base = base.rstrip("/")
        self.token = token
        self.timeout = timeout

    def _headers(self, extra: dict[str, str] | None = None) -> dict[str, str]:
        h = dict(extra or {})
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _request(
        self,
        method: str,
        url: str,
        data: bytes | None = None,
        headers: dict[str, str] | None = None,
    ):
        req = urlrequest.Request(
            url, data=data, method=method, headers=self._headers(headers)
        )
        return urlrequest.urlopen(req, timeout=self.timeout)

    def ping(self) -> None:
        try:
            self._request("GET", f"{self.base}/v2/").close()
        except urlerror.URLError as e:
            raise RegistryError(f"registry {self.base} unreachable: {e}") from e

    def has_blob(self, repo: str, digest: str) -> bool:
        try:
            self._request(
                "HEAD", f"{self.base}/v2/{repo}/blobs/{digest}"
            ).close()
            return True
        except urlerror.HTTPError as e:
            if e.code == 404:
                return False
            raise RegistryError(f"blob HEAD {digest}: HTTP {e.code}") from e

    def upload_blob(self, repo: str, digest: str, data: bytes) -> None:
        if self.has_blob(repo, digest):
            return  # cross-build layer dedup, the registry's whole point
        try:
            with self._request(
                "POST", f"{self.base}/v2/{repo}/blobs/uploads/"
            ) as resp:
                location = resp.headers.get("Location")
            if not location:
                raise RegistryError("upload POST returned no Location")
            if location.startswith("/"):
                location = self.base + location
            sep = "&" if "?" in location else "?"
            self._request(
                "PUT",
                f"{location}{sep}digest={digest}",
                data=data,
                headers={"Content-Type": "application/octet-stream"},
            ).close()
        except urlerror.HTTPError as e:
            raise RegistryError(f"blob upload {digest}: HTTP {e.code}") from e

    def put_manifest(self, repo: str, reference: str, image: OciImage) -> str:
        try:
            with self._request(
                "PUT",
                f"{self.base}/v2/{repo}/manifests/{reference}",
                data=image.manifest,
                headers={"Content-Type": MANIFEST_MEDIA_TYPE},
            ) as resp:
                return resp.headers.get(
                    "Docker-Content-Digest", image.manifest_digest
                )
        except urlerror.HTTPError as e:
            raise RegistryError(
                f"manifest PUT {reference}: HTTP {e.code}"
            ) from e

    def get_manifest(self, repo: str, reference: str) -> tuple[bytes, str]:
        try:
            with self._request(
                "GET",
                f"{self.base}/v2/{repo}/manifests/{reference}",
                headers={"Accept": MANIFEST_MEDIA_TYPE},
            ) as resp:
                body = resp.read()
                return body, resp.headers.get(
                    "Docker-Content-Digest", digest_of(body)
                )
        except urlerror.HTTPError as e:
            raise RegistryError(
                f"manifest GET {reference}: HTTP {e.code}"
            ) from e


def push_image(
    image: OciImage,
    registry: str,
    repo: str,
    tags: list[str],
    *,
    token: str | None = None,
) -> dict[str, Any]:
    """Push blobs + manifest (once per tag). Returns the deploy-consumable
    reference block: a digest-pinned ref (immutable — what production
    manifests should pin) plus the mutable tag refs."""
    client = RegistryClient(registry, token)
    client.ping()
    client.upload_blob(repo, image.layer_digest, image.layer)
    client.upload_blob(repo, image.config_digest, image.config)
    for tag in tags:
        client.put_manifest(repo, tag, image)
    host = registry.split("://", 1)[-1]
    return {
        "registry": registry,
        "repository": repo,
        "digest": image.manifest_digest,
        "ref": f"{host}/{repo}@{image.manifest_digest}",
        "tag_refs": [f"{host}/{repo}:{t}" for t in tags],
        "tags": list(tags),
    }
