"""In-memory Docker/OCI registry speaking the Registry HTTP API v2.

The contract-test double for the release pipeline's push leg (the role a
local `registry:2` container plays in CI elsewhere) — and a usable local
registry for air-gapped dev loops. Covers the subset a pusher/puller needs:

  GET  /v2/                               liveness
  HEAD/GET /v2/{repo}/blobs/{digest}      blob existence / fetch
  POST /v2/{repo}/blobs/uploads/          start upload (returns Location)
  PUT  {location}?digest=...              monolithic upload, digest-verified
  PUT  /v2/{repo}/manifests/{ref}         tag or digest push
  GET  /v2/{repo}/manifests/{ref}         by tag or digest
  GET  /v2/{repo}/tags/list

Parity: the reference's release pipeline pushes through a real gcr.io
(py/build_and_push_image.py:15-25); the rebuild proves the same wire
contract against this stub in tests/test_harness.py.
"""

from __future__ import annotations

import hashlib
import json
import re
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_BLOB_RE = re.compile(r"^/v2/(?P<repo>.+)/blobs/(?P<digest>sha256:[0-9a-f]{64})$")
_UPLOAD_START_RE = re.compile(r"^/v2/(?P<repo>.+)/blobs/uploads/$")
_UPLOAD_RE = re.compile(r"^/v2/(?P<repo>.+)/blobs/uploads/(?P<uid>[0-9a-f-]+)$")
_MANIFEST_RE = re.compile(r"^/v2/(?P<repo>.+)/manifests/(?P<ref>[^/]+)$")
_TAGS_RE = re.compile(r"^/v2/(?P<repo>.+)/tags/list$")


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "RegistryStub"

    def _reply(self, code: int, body: bytes = b"", headers: dict | None = None):
        self.send_response(code)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def _error(self, code: int, errcode: str, message: str):
        body = json.dumps(
            {"errors": [{"code": errcode, "message": message}]}
        ).encode()
        self._reply(code, body, {"Content-Type": "application/json"})

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n else b""

    # -- GET/HEAD -----------------------------------------------------------

    def do_GET(self):  # noqa: N802
        path = self.path.split("?", 1)[0]
        if path == "/v2/" or path == "/v2":
            self._reply(200, b"{}", {"Content-Type": "application/json"})
            return
        if m := _BLOB_RE.match(path):
            with self.server.lock:
                blob = self.server.blobs.get(m["digest"])
            if blob is None:
                self._error(404, "BLOB_UNKNOWN", m["digest"])
                return
            self._reply(
                200, blob,
                {"Content-Type": "application/octet-stream",
                 "Docker-Content-Digest": m["digest"]},
            )
            return
        if m := _MANIFEST_RE.match(path):
            key = (m["repo"], m["ref"])
            with self.server.lock:
                digest = self.server.tags.get(key) or (
                    m["ref"] if m["ref"].startswith("sha256:") else None
                )
                manifest = self.server.manifests.get((m["repo"], digest))
            if manifest is None:
                self._error(404, "MANIFEST_UNKNOWN", m["ref"])
                return
            self._reply(
                200, manifest["bytes"],
                {"Content-Type": manifest["media_type"],
                 "Docker-Content-Digest": digest},
            )
            return
        if m := _TAGS_RE.match(path):
            with self.server.lock:
                tags = sorted(
                    t for (repo, t) in self.server.tags if repo == m["repo"]
                )
            self._reply(
                200,
                json.dumps({"name": m["repo"], "tags": tags}).encode(),
                {"Content-Type": "application/json"},
            )
            return
        self._error(404, "UNSUPPORTED", path)

    do_HEAD = do_GET  # noqa: N815 — HEAD shares routing, _reply omits body

    # -- uploads ------------------------------------------------------------

    def do_POST(self):  # noqa: N802
        path = self.path.split("?", 1)[0]
        if m := _UPLOAD_START_RE.match(path):
            uid = str(uuid.uuid4())
            with self.server.lock:
                self.server.uploads[uid] = b""
            self._reply(
                202, b"",
                {"Location": f"/v2/{m['repo']}/blobs/uploads/{uid}",
                 "Docker-Upload-UUID": uid},
            )
            return
        self._error(404, "UNSUPPORTED", path)

    def do_PATCH(self):  # noqa: N802 — chunked upload leg
        path, _, _query = self.path.partition("?")
        if m := _UPLOAD_RE.match(path):
            data = self._body()
            with self.server.lock:
                if m["uid"] not in self.server.uploads:
                    self._error(404, "BLOB_UPLOAD_UNKNOWN", m["uid"])
                    return
                self.server.uploads[m["uid"]] += data
                total = len(self.server.uploads[m["uid"]])
            self._reply(
                202, b"",
                {"Location": f"/v2/{m['repo']}/blobs/uploads/{m['uid']}",
                 "Range": f"0-{total - 1}"},
            )
            return
        self._error(404, "UNSUPPORTED", path)

    def do_PUT(self):  # noqa: N802
        path, _, query = self.path.partition("?")
        if m := _UPLOAD_RE.match(path):
            params = dict(
                kv.split("=", 1) for kv in query.split("&") if "=" in kv
            )
            digest = params.get("digest", "")
            data = self._body()
            with self.server.lock:
                data = self.server.uploads.pop(m["uid"], b"") + data
            actual = "sha256:" + hashlib.sha256(data).hexdigest()
            if digest != actual:
                self._error(
                    400, "DIGEST_INVALID", f"want {digest}, got {actual}"
                )
                return
            with self.server.lock:
                self.server.blobs[digest] = data
            self._reply(
                201, b"",
                {"Location": f"/v2/{m['repo']}/blobs/{digest}",
                 "Docker-Content-Digest": digest},
            )
            return
        if m := _MANIFEST_RE.match(path):
            body = self._body()
            digest = "sha256:" + hashlib.sha256(body).hexdigest()
            if m["ref"].startswith("sha256:") and m["ref"] != digest:
                self._error(400, "DIGEST_INVALID", m["ref"])
                return
            # Reject manifests whose referenced blobs were never pushed —
            # the ordering contract (blobs before manifest) real registries
            # enforce.
            try:
                doc = json.loads(body)
                refs = [doc["config"]["digest"]] + [
                    layer["digest"] for layer in doc["layers"]
                ]
            except (ValueError, KeyError, TypeError):
                self._error(400, "MANIFEST_INVALID", "unparseable manifest")
                return
            with self.server.lock:
                missing = [d for d in refs if d not in self.server.blobs]
            if missing:
                self._error(
                    400, "MANIFEST_BLOB_UNKNOWN", ", ".join(missing)
                )
                return
            media = self.headers.get(
                "Content-Type", "application/vnd.oci.image.manifest.v1+json"
            )
            with self.server.lock:
                self.server.manifests[(m["repo"], digest)] = {
                    "bytes": body, "media_type": media,
                }
                if not m["ref"].startswith("sha256:"):
                    self.server.tags[(m["repo"], m["ref"])] = digest
            self._reply(201, b"", {"Docker-Content-Digest": digest})
            return
        self._error(404, "UNSUPPORTED", path)

    def log_message(self, fmt, *args):
        pass


class RegistryStub(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _Handler)
        self.blobs: dict[str, bytes] = {}
        self.manifests: dict[tuple[str, str], dict] = {}
        self.tags: dict[tuple[str, str], str] = {}
        self.uploads: dict[str, bytes] = {}
        self.lock = threading.Lock()

    @property
    def url(self) -> str:
        return f"http://{self.server_address[0]}:{self.server_address[1]}"

    def start(self) -> threading.Thread:
        t = threading.Thread(
            target=self.serve_forever, name="registry-stub", daemon=True
        )
        t.start()
        return t

    def stop(self) -> None:
        self.shutdown()
