// Host-side image augmentation: random/center crop + horizontal flip over
// uint8 batches, threaded off the GIL.
//
// The preprocessing half of the native input path (record_pipeline.cc does
// IO; this does the per-image work between records and the device): TPU
// training keeps images uint8 end-to-end on the host and normalizes on
// device, so the host cost is pure byte movement — which is exactly what a
// C++ loop with threads does well and a Python per-image loop does not.
//
// Determinism contract (shared with the Python fallback in
// native/augment.py and with record_pipeline's shuffle): per-image
// decisions derive from splitmix64(seed * 1000003 + global_index), so
// native and Python engines produce BIT-IDENTICAL output for the same
// (seed, index) stream and tests can assert equivalence.
//
// C ABI:
//   aug_batch(in, out, n, in_h, in_w, ch, out_h, out_w, seed, index0,
//             train, threads, in_stride) -> 0 ok, <0 bad args
//   in_stride: bytes between consecutive source images (0 => contiguous,
//   i.e. in_h*in_w*ch); lets the crop consume raw record buffers directly.
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64_next(uint64_t* s) {
  *s += 0x9E3779B97F4A7C15ull;
  uint64_t z = *s;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

struct Args {
  const uint8_t* in;
  uint8_t* out;
  uint64_t in_h, in_w, ch, out_h, out_w, seed, index0;
  // Byte distance between consecutive source images: lets the crop read
  // straight out of a raw RECORDS buffer (image bytes + trailing label
  // byte per record) with no intermediate slice-and-reshape copy of the
  // whole batch on the Python side.
  uint64_t in_stride;
  int train;
};

// Domain separator: keeps augment decision streams disjoint from the
// record-pipeline shuffle streams (epoch_order keys seed*1000003+epoch in
// the same splitmix64 keyspace) even when a user passes one seed to both.
constexpr uint64_t kAugmentDomain = 0x6175676D656E7400ull;  // "augment\0"

void one_image(const Args& a, uint64_t i) {
  uint64_t s = ((a.seed * 1000003ull + a.index0 + i) ^ kAugmentDomain) ^
               0x9E3779B97F4A7C15ull;
  uint64_t max_y = a.in_h - a.out_h, max_x = a.in_w - a.out_w;
  uint64_t y, x;
  bool flip;
  if (a.train) {
    y = max_y ? splitmix64_next(&s) % (max_y + 1) : 0;
    x = max_x ? splitmix64_next(&s) % (max_x + 1) : 0;
    flip = splitmix64_next(&s) & 1;
  } else {  // eval: deterministic center crop, no flip
    y = max_y / 2;
    x = max_x / 2;
    flip = false;
  }
  const uint8_t* src = a.in + i * a.in_stride;
  uint8_t* dst = a.out + i * a.out_h * a.out_w * a.ch;
  for (uint64_t r = 0; r < a.out_h; ++r) {
    const uint8_t* row = src + ((y + r) * a.in_w + x) * a.ch;
    uint8_t* drow = dst + r * a.out_w * a.ch;
    if (!flip) {
      std::memcpy(drow, row, a.out_w * a.ch);
    } else if (a.ch == 3) {
      // RGB fast path: a runtime-sized memcpy(.., .., 3) per pixel is a
      // real function call the compiler cannot inline — it dominated the
      // whole augment stage (~50% of train images flip). Constant-size
      // copies compile to plain byte moves.
      for (uint64_t c = 0; c < a.out_w; ++c) {
        const uint8_t* s3 = row + (a.out_w - 1 - c) * 3;
        uint8_t* d3 = drow + c * 3;
        d3[0] = s3[0];
        d3[1] = s3[1];
        d3[2] = s3[2];
      }
    } else {
      for (uint64_t c = 0; c < a.out_w; ++c) {
        std::memcpy(drow + c * a.ch, row + (a.out_w - 1 - c) * a.ch, a.ch);
      }
    }
  }
}

}  // namespace

// Gather form: image i comes from base + indices[i] * record_stride — the
// zero-copy host path for page-cache-resident record files. With an
// mmap'd file the ONLY host byte movement per image is the crop write
// itself; there is no loader read, no batch assembly, no glue copy. On a
// single-core host this roughly doubles input throughput over the
// pread-ring + strided-augment path (~3.3k -> ~7k img/s at 256^2 -> 224^2
// bench shapes).
extern "C" int aug_gather(const uint8_t* base, const uint64_t* indices,
                          uint8_t* out, uint64_t n, uint64_t record_stride,
                          uint64_t in_h, uint64_t in_w, uint64_t ch,
                          uint64_t out_h, uint64_t out_w, uint64_t seed,
                          uint64_t index0, int train, int threads) {
  if (!base || !indices || !out || out_h > in_h || out_w > in_w || ch == 0)
    return -1;
  if (record_stride < in_h * in_w * ch) return -1;
  uint64_t t = threads > 0 ? static_cast<uint64_t>(threads) : 1;
  uint64_t hw = std::thread::hardware_concurrency();
  if (hw && t > hw) t = hw;
  if (t > n) t = n ? n : 1;
  auto run = [&](uint64_t w, uint64_t stride_threads) {
    for (uint64_t i = w; i < n; i += stride_threads) {
      Args a{base + indices[i] * record_stride, out + i * out_h * out_w * ch,
             in_h, in_w, ch, out_h, out_w, seed, index0 + i,
             in_h * in_w * ch, train};
      one_image(a, 0);
    }
  };
  if (t <= 1) {
    run(0, 1);
    return 0;
  }
  std::vector<std::thread> pool;
  pool.reserve(t);
  for (uint64_t w = 0; w < t; ++w) pool.emplace_back(run, w, t);
  for (auto& th : pool) th.join();
  return 0;
}

extern "C" int aug_batch(const uint8_t* in, uint8_t* out, uint64_t n,
                         uint64_t in_h, uint64_t in_w, uint64_t ch,
                         uint64_t out_h, uint64_t out_w, uint64_t seed,
                         uint64_t index0, int train, int threads,
                         uint64_t in_stride) {
  if (!in || !out || out_h > in_h || out_w > in_w || ch == 0) return -1;
  if (in_stride == 0) in_stride = in_h * in_w * ch;
  if (in_stride < in_h * in_w * ch) return -1;
  Args a{in, out, in_h, in_w, ch, out_h, out_w, seed, index0, in_stride,
         train};
  uint64_t t = threads > 0 ? static_cast<uint64_t>(threads) : 1;
  // More threads than cores just adds spawn/contention cost for a
  // memory-bound loop (observed on single-core CI hosts).
  uint64_t hw = std::thread::hardware_concurrency();
  if (hw && t > hw) t = hw;
  if (t > n) t = n ? n : 1;
  if (t <= 1) {
    for (uint64_t i = 0; i < n; ++i) one_image(a, i);
    return 0;
  }
  std::vector<std::thread> pool;
  pool.reserve(t);
  for (uint64_t w = 0; w < t; ++w) {
    pool.emplace_back([&, w]() {
      for (uint64_t i = w; i < n; i += t) one_image(a, i);
    });
  }
  for (auto& th : pool) th.join();
  return 0;
}
