// Threaded prefetching record loader — the framework's native data plane.
//
// Role: the host-side input pipeline that keeps a TPU fed (HBM is idle while
// the host blocks on IO; the reference delegates this entirely to
// tf.data inside the user's container — SURVEY.md notes the repo itself has
// zero native code, so this is a capability the rebuild adds with real
// C++ rather than a Python thread pool throttled by the GIL).
//
// Semantics:
//  - a file of fixed-size records (n = file_size / record_bytes)
//  - epochs iterate every record exactly once; optional per-epoch
//    Fisher-Yates shuffle from a splitmix64/xorshift PRNG seeded by
//    (seed, epoch) => deterministic given the seed
//  - multi-host sharding: all shards compute the SAME epoch order, then
//    shard k consumes positions k, k+num_shards, ..., truncated to the
//    common floor(n / num_shards) length — shards are disjoint and all
//    exactly equal-sized (lockstep hosts), the <num_shards remainder is
//    dropped for the epoch, and the shuffle re-deals between epochs
//  - worker threads pread() record runs into batch slots; a bounded ring
//    of filled slots decouples producers from the consumer
//  - dp_next() hands back one batch (blocking), in batch order
//  - loop=0: one epoch then EOF (0 return); loop=1: epochs forever
//
// C ABI (ctypes-friendly); thread-safe for one consumer.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Prng {
  uint64_t s;
  explicit Prng(uint64_t seed) : s(seed ^ 0x9e3779b97f4a7c15ULL) {}
  uint64_t next() {
    // splitmix64
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  // unbiased bounded draw (Lemire)
  uint64_t bounded(uint64_t n) { return n ? next() % n : 0; }
};

struct Batch {
  std::vector<char> data;
  uint64_t records = 0;
  uint64_t seq = 0;
};

// One definition of the epoch order (identity + optional Fisher-Yates +
// equal-size strided shard slice), shared by the in-engine reshuffle and
// the standalone dp_epoch_order export so the two can never drift.
std::vector<uint64_t> compute_epoch_order(uint64_t num_records, uint64_t seed,
                                          uint64_t epoch, bool shuffle,
                                          uint64_t shard_id,
                                          uint64_t num_shards) {
  std::vector<uint64_t> order(num_records);
  for (uint64_t i = 0; i < num_records; i++) order[i] = i;
  if (shuffle && num_records > 1) {
    Prng rng(seed * 1000003ULL + epoch);
    for (uint64_t i = num_records - 1; i > 0; i--) {
      uint64_t j = rng.bounded(i + 1);
      std::swap(order[i], order[j]);
    }
  }
  if (num_shards > 1) {
    std::vector<uint64_t> mine;
    uint64_t keep = num_records / num_shards;  // equal-size shards
    for (uint64_t i = shard_id; i < order.size() && mine.size() < keep;
         i += num_shards)
      mine.push_back(order[i]);
    order = std::move(mine);
  }
  return order;
}

struct Pipeline {
  int fd = -1;
  uint64_t record_bytes = 0;
  uint64_t batch = 0;
  uint64_t num_records = 0;
  bool shuffle = false;
  bool loop = false;
  uint64_t seed = 0;
  uint64_t shard_id = 0;
  uint64_t num_shards = 1;

  // work assignment
  std::vector<uint64_t> order;   // record indices for the current epoch
  uint64_t epoch = 0;
  uint64_t next_batch_to_claim = 0;   // producer cursor (batch index in epoch)
  uint64_t batches_per_epoch = 0;

  // slot ring (filled batches, delivered in seq order)
  std::vector<Batch> ring;
  uint64_t capacity = 0;
  uint64_t next_seq_to_produce = 0;   // global batch sequence
  uint64_t next_seq_to_consume = 0;
  std::vector<bool> filled;

  std::mutex mu;
  std::condition_variable cv_produce;
  std::condition_variable cv_consume;
  std::atomic<bool> stop{false};
  bool io_error = false;
  std::vector<std::thread> workers;

  void reshuffle_locked() {
    order = compute_epoch_order(num_records, seed, epoch, shuffle,
                                shard_id, num_shards);
  }

  // Claim the next batch of this epoch (or roll the epoch / signal done).
  // Returns false when there is no more work forever.
  bool claim(uint64_t* seq_out, std::vector<uint64_t>* records_out) {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      if (stop.load()) return false;
      if (next_batch_to_claim < batches_per_epoch) {
        uint64_t b = next_batch_to_claim++;
        uint64_t lo = b * batch;
        uint64_t hi = std::min((uint64_t)order.size(), lo + batch);
        records_out->assign(order.begin() + lo, order.begin() + hi);
        *seq_out = next_seq_to_produce++;
        return true;
      }
      if (!loop) {
        return false;
      }
      epoch++;
      reshuffle_locked();
      next_batch_to_claim = 0;
    }
  }

  void worker() {
    std::vector<uint64_t> recs;
    uint64_t seq;
    while (claim(&seq, &recs)) {
      // Wait for the ring slot BEFORE reading, then pread straight into the
      // slot's preallocated buffer. The previous shape (read into a fresh
      // vector, move into the ring, shrink_to_fit on consume) paid a 62 MB
      // malloc + zero-page faulting + free on EVERY batch at bench shapes —
      // the dominant cost of the single-core loader. Slot exclusivity: seq
      // values are unique and the window admits at most one in-flight seq
      // per slot (window size == capacity).
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_produce.wait(lk, [&] {
          return stop.load() || seq < next_seq_to_consume + capacity;
        });
        if (stop.load()) return;
      }
      Batch& b = ring[seq % capacity];
      b.seq = seq;
      b.records = recs.size();
      bool ok = true;
      for (size_t i = 0; i < recs.size(); i++) {
        ssize_t got = pread(fd, b.data.data() + i * record_bytes,
                            record_bytes, (off_t)(recs[i] * record_bytes));
        if (got != (ssize_t)record_bytes) { ok = false; break; }
      }
      std::unique_lock<std::mutex> lk(mu);
      if (stop.load()) return;
      if (!ok) { io_error = true; cv_consume.notify_all(); return; }
      filled[seq % capacity] = true;
      cv_consume.notify_all();
    }
    // No more work (non-loop EOF or stop): the consumer detects EOF from
    // next_seq_to_consume >= batches_per_epoch, no flag needed.
  }
};

}  // namespace

extern "C" {

void* dp_open(const char* path, uint64_t record_bytes, uint64_t batch,
              uint64_t prefetch, uint64_t threads, uint64_t seed,
              int shuffle, int loop, uint64_t shard_id,
              uint64_t num_shards) {
  if (record_bytes == 0 || batch == 0) return nullptr;
  if (num_shards == 0 || shard_id >= num_shards) return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0 ||
      (uint64_t)st.st_size % record_bytes != 0) {
    close(fd);
    return nullptr;
  }
  auto* p = new Pipeline();
  p->fd = fd;
  p->record_bytes = record_bytes;
  p->batch = batch;
  p->num_records = (uint64_t)st.st_size / record_bytes;
  p->shuffle = shuffle != 0;
  p->loop = loop != 0;
  p->seed = seed;
  p->shard_id = shard_id;
  p->num_shards = num_shards;
  // Equal-size shards: every shard gets exactly floor(n / num_shards)
  // records per epoch (lockstep multi-host contract).
  uint64_t mine = p->num_records / num_shards;
  if (mine == 0) {  // empty shard: more shards than records
    close(fd);
    delete p;
    return nullptr;
  }
  p->batches_per_epoch = (mine + batch - 1) / batch;
  p->capacity = prefetch ? prefetch : 4;
  p->ring.resize(p->capacity);
  for (auto& slot : p->ring) slot.data.resize(batch * record_bytes);
  p->filled.assign(p->capacity, false);
  p->reshuffle_locked();
  uint64_t n_threads = threads ? threads : 2;
  for (uint64_t i = 0; i < n_threads; i++)
    p->workers.emplace_back(&Pipeline::worker, p);
  return p;
}

// Blocks for the next batch. Returns number of records copied into out
// (record_bytes each), 0 on EOF, -1 on error/undersized buffer.
int64_t dp_next(void* handle, char* out, uint64_t out_bytes) {
  auto* p = static_cast<Pipeline*>(handle);
  if (!p) return -1;
  std::unique_lock<std::mutex> lk(p->mu);
  if (!p->loop && p->next_seq_to_consume >= p->batches_per_epoch)
    return 0;  // clean EOF: every batch of the single epoch was consumed
  p->cv_consume.wait(lk, [&] {
    return p->stop.load() || p->io_error ||
           p->filled[p->next_seq_to_consume % p->capacity];
  });
  if (p->stop.load() || p->io_error) return -1;
  uint64_t slot = p->next_seq_to_consume % p->capacity;
  Batch& b = p->ring[slot];
  uint64_t bytes = b.records * p->record_bytes;
  if (bytes > out_bytes) return -1;
  std::memcpy(out, b.data.data(), bytes);
  int64_t n = (int64_t)b.records;
  p->filled[slot] = false;
  p->next_seq_to_consume++;
  p->cv_produce.notify_all();
  return n;
}

// Epoch order as a standalone export: the Python-side MMapRecordPipeline
// (and any gather-style consumer) needs the same order the in-engine
// shuffle produces, and the interpreter's Fisher-Yates loop is ~1000x
// slower at million-record scale. Writes min(out_len, shard length)
// indices; returns the shard length, or -1 on bad args.
int64_t dp_epoch_order(uint64_t num_records, uint64_t seed, uint64_t epoch,
                       int shuffle, uint64_t shard_id, uint64_t num_shards,
                       uint64_t* out, uint64_t out_len) {
  if (!out || num_shards == 0 || shard_id >= num_shards) return -1;
  std::vector<uint64_t> order = compute_epoch_order(
      num_records, seed, epoch, shuffle != 0, shard_id, num_shards);
  std::memcpy(out, order.data(),
              std::min(out_len, (uint64_t)order.size()) * sizeof(uint64_t));
  return (int64_t)order.size();
}

uint64_t dp_num_records(void* handle) {
  auto* p = static_cast<Pipeline*>(handle);
  return p ? p->num_records : 0;
}

uint64_t dp_batches_per_epoch(void* handle) {
  auto* p = static_cast<Pipeline*>(handle);
  return p ? p->batches_per_epoch : 0;
}

void dp_close(void* handle) {
  auto* p = static_cast<Pipeline*>(handle);
  if (!p) return;
  p->stop.store(true);
  p->cv_produce.notify_all();
  p->cv_consume.notify_all();
  for (auto& t : p->workers) t.join();
  close(p->fd);
  delete p;
}

}  // extern "C"
