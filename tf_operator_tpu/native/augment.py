"""Batch image augmentation: random/center crop + horizontal flip (uint8).

Python binding for augment.cc with a pure-NumPy fallback of IDENTICAL
semantics — per-image decisions derive from the shared splitmix64 stream
(seed * 1000003 + global_index), so the two engines are bit-interchangeable
and tests assert exact equivalence. Together with RecordPipeline this is
the host half of the input path: records -> shuffle -> crop/flip -> uint8
batch -> device (normalization happens on device; bytes stay uint8 on the
host and over the transfer).
"""

from __future__ import annotations

import ctypes

import numpy as np

from tf_operator_tpu.native import NativeBuildError, load_library
from tf_operator_tpu.native.pipeline import _splitmix64_stream
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="native-augment")

_lib = None
_lib_failed = False


def _native_lib():
    global _lib, _lib_failed
    if _lib is None and not _lib_failed:
        try:
            lib = load_library("augment.cc")
            lib.aug_batch.restype = ctypes.c_int
            lib.aug_batch.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
                ctypes.c_uint64,
            ]
            lib.aug_gather.restype = ctypes.c_int
            lib.aug_gather.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
            ]
            _lib = lib
        except NativeBuildError as e:
            LOG.warning("native augment unavailable (%s); numpy engine", e)
            _lib_failed = True
    return _lib


# Domain separator (must match augment.cc): keeps augment decision streams
# disjoint from the record-pipeline shuffle streams, which key the same
# splitmix64 keyspace as seed*1000003+epoch.
_AUGMENT_DOMAIN = 0x6175676D656E7400  # "augment\0"
_MASK64 = (1 << 64) - 1


def _decisions(seed: int, index: int, max_y: int, max_x: int,
               train: bool) -> tuple[int, int, bool]:
    if not train:
        return max_y // 2, max_x // 2, False
    rng = _splitmix64_stream(((seed * 1000003 + index) & _MASK64) ^ _AUGMENT_DOMAIN)
    y = next(rng) % (max_y + 1) if max_y else 0
    x = next(rng) % (max_x + 1) if max_x else 0
    return y, x, bool(next(rng) & 1)


def augment_batch(
    images: np.ndarray,
    out_hw: tuple[int, int],
    *,
    seed: int = 0,
    index0: int = 0,
    train: bool = True,
    threads: int = 4,
    engine: str = "auto",
) -> np.ndarray:
    """Crop + flip: random crop with random hflip when ``train``; a
    deterministic center crop with NO flip otherwise.

    images: [n, H, W, C] uint8 (C-contiguous). index0 is the global index of
    images[0] in the sample stream — it keys the per-image RNG so results
    are reproducible across batch boundaries and engines.
    """
    if images.dtype != np.uint8 or images.ndim != 4:
        raise ValueError(f"expected [n,H,W,C] uint8, got {images.dtype} {images.shape}")
    n, in_h, in_w, ch = images.shape
    images = np.ascontiguousarray(images)
    return _augment(
        images, n, in_h, in_w, ch, in_h * in_w * ch, out_hw,
        seed=seed, index0=index0, train=train, threads=threads,
        engine=engine,
    )


def augment_records(
    records: np.ndarray,
    image_shape: tuple[int, int, int],
    out_hw: tuple[int, int],
    *,
    seed: int = 0,
    index0: int = 0,
    train: bool = True,
    threads: int = 4,
    engine: str = "auto",
    out: np.ndarray | None = None,
) -> np.ndarray:
    """Crop + flip directly from a raw record batch ([n, record_bytes]
    uint8, each record = H*W*C image bytes + trailing metadata such as a
    label byte). Skips the slice-and-reshape that materializes a full image
    batch copy between the record loader and the augmenter — the per-image
    record stride goes straight into the native kernel. Identical output to
    ``augment_batch(records[:, :H*W*C].reshape(n,H,W,C), ...)``.
    """
    if records.dtype != np.uint8 or records.ndim != 2:
        raise ValueError(
            f"expected [n, record_bytes] uint8, got {records.dtype} "
            f"{records.shape}"
        )
    in_h, in_w, ch = image_shape
    img_bytes = in_h * in_w * ch
    n, rec_bytes = records.shape
    if rec_bytes < img_bytes:
        raise ValueError(
            f"record_bytes {rec_bytes} < image bytes {img_bytes}"
        )
    records = np.ascontiguousarray(records)
    return _augment(
        records, n, in_h, in_w, ch, rec_bytes, out_hw,
        seed=seed, index0=index0, train=train, threads=threads,
        engine=engine, out=out,
    )


def augment_gather(
    base: np.ndarray,
    indices: np.ndarray,
    record_stride: int,
    image_shape: tuple[int, int, int],
    out_hw: tuple[int, int],
    *,
    seed: int = 0,
    index0: int = 0,
    train: bool = True,
    threads: int = 4,
    engine: str = "auto",
    out: np.ndarray | None = None,
) -> np.ndarray:
    """Crop + flip gathering records straight out of ``base`` (a flat uint8
    buffer, typically an ``np.memmap`` of the record file): image i lives at
    ``base[indices[i] * record_stride:]``. The zero-copy host input path —
    for a page-cache-resident file the only byte movement per image is the
    crop write. Decision stream identical to the other entry points
    (per-image key = seed, index0 + i)."""
    if base.dtype != np.uint8 or base.ndim != 1:
        raise ValueError(f"base must be flat uint8, got {base.dtype} {base.shape}")
    in_h, in_w, ch = image_shape
    img_bytes = in_h * in_w * ch
    if record_stride < img_bytes:
        raise ValueError(f"record_stride {record_stride} < image bytes {img_bytes}")
    idx = np.ascontiguousarray(indices, dtype=np.uint64)
    n = int(idx.shape[0])
    if n and int(idx.max()) * record_stride + img_bytes > base.size:
        raise ValueError("index out of range for base buffer")
    out_h, out_w = out_hw
    if out_h > in_h or out_w > in_w:
        raise ValueError(f"crop {out_hw} larger than input {(in_h, in_w)}")
    out = _validate_out(out, n, out_h, out_w, ch)
    lib = _resolve_engine(engine)
    if lib is not None:
        rc = lib.aug_gather(
            base.ctypes.data_as(ctypes.c_char_p),
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            out.ctypes.data_as(ctypes.c_char_p),
            n, record_stride, in_h, in_w, ch, out_h, out_w,
            seed, index0, int(train), threads,
        )
        if rc != 0:
            raise ValueError(f"aug_gather failed with rc={rc}")
        return out
    for i in range(n):
        y, x, flip = _decisions(seed, index0 + i, in_h - out_h, in_w - out_w, train)
        off = int(idx[i]) * record_stride
        img = base[off:off + img_bytes].reshape(in_h, in_w, ch)
        crop = img[y:y + out_h, x:x + out_w]
        out[i] = crop[:, ::-1] if flip else crop
    return out


def _validate_out(
    out: np.ndarray | None, n: int, out_h: int, out_w: int, ch: int
) -> np.ndarray:
    """Allocate the output batch, or validate a caller-provided buffer
    (writing through one — e.g. a slot of a stacked multi-step batch —
    skips a whole-output copy per batch)."""
    if out is None:
        return np.empty((n, out_h, out_w, ch), np.uint8)
    if (out.shape != (n, out_h, out_w, ch) or out.dtype != np.uint8
            or not out.flags["C_CONTIGUOUS"]):
        raise ValueError(
            f"out must be C-contiguous uint8 {(n, out_h, out_w, ch)}, got "
            f"{out.dtype} {out.shape}"
        )
    return out


def _resolve_engine(engine: str):
    """The native library to use, or None for the numpy fallback."""
    if engine not in ("auto", "native", "python"):
        raise ValueError(f"unknown engine {engine!r}")
    lib = _native_lib() if engine in ("auto", "native") else None
    if engine == "native" and lib is None:
        raise NativeBuildError("native augment engine unavailable")
    return lib


def _augment(
    src: np.ndarray, n: int, in_h: int, in_w: int, ch: int, in_stride: int,
    out_hw: tuple[int, int], *, seed: int, index0: int, train: bool,
    threads: int, engine: str, out: np.ndarray | None = None,
) -> np.ndarray:
    out_h, out_w = out_hw
    if out_h > in_h or out_w > in_w:
        raise ValueError(f"crop {out_hw} larger than input {(in_h, in_w)}")
    out = _validate_out(out, n, out_h, out_w, ch)
    lib = _resolve_engine(engine)
    if lib is not None:
        rc = lib.aug_batch(
            src.ctypes.data_as(ctypes.c_char_p),
            out.ctypes.data_as(ctypes.c_char_p),
            n, in_h, in_w, ch, out_h, out_w, seed, index0,
            int(train), threads, in_stride,
        )
        if rc != 0:
            raise ValueError(f"aug_batch failed with rc={rc}")
        return out

    flat = src.reshape(n, -1)
    for i in range(n):
        y, x, flip = _decisions(seed, index0 + i, in_h - out_h, in_w - out_w, train)
        img = flat[i, : in_h * in_w * ch].reshape(in_h, in_w, ch)
        crop = img[y:y + out_h, x:x + out_w]
        out[i] = crop[:, ::-1] if flip else crop
    return out
