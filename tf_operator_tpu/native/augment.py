"""Batch image augmentation: random/center crop + horizontal flip (uint8).

Python binding for augment.cc with a pure-NumPy fallback of IDENTICAL
semantics — per-image decisions derive from the shared splitmix64 stream
(seed * 1000003 + global_index), so the two engines are bit-interchangeable
and tests assert exact equivalence. Together with RecordPipeline this is
the host half of the input path: records -> shuffle -> crop/flip -> uint8
batch -> device (normalization happens on device; bytes stay uint8 on the
host and over the transfer).
"""

from __future__ import annotations

import ctypes

import numpy as np

from tf_operator_tpu.native import NativeBuildError, load_library
from tf_operator_tpu.native.pipeline import _splitmix64_stream
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="native-augment")

_lib = None
_lib_failed = False


def _native_lib():
    global _lib, _lib_failed
    if _lib is None and not _lib_failed:
        try:
            lib = load_library("augment.cc")
            lib.aug_batch.restype = ctypes.c_int
            lib.aug_batch.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
            ]
            _lib = lib
        except NativeBuildError as e:
            LOG.warning("native augment unavailable (%s); numpy engine", e)
            _lib_failed = True
    return _lib


# Domain separator (must match augment.cc): keeps augment decision streams
# disjoint from the record-pipeline shuffle streams, which key the same
# splitmix64 keyspace as seed*1000003+epoch.
_AUGMENT_DOMAIN = 0x6175676D656E7400  # "augment\0"
_MASK64 = (1 << 64) - 1


def _decisions(seed: int, index: int, max_y: int, max_x: int,
               train: bool) -> tuple[int, int, bool]:
    if not train:
        return max_y // 2, max_x // 2, False
    rng = _splitmix64_stream(((seed * 1000003 + index) & _MASK64) ^ _AUGMENT_DOMAIN)
    y = next(rng) % (max_y + 1) if max_y else 0
    x = next(rng) % (max_x + 1) if max_x else 0
    return y, x, bool(next(rng) & 1)


def augment_batch(
    images: np.ndarray,
    out_hw: tuple[int, int],
    *,
    seed: int = 0,
    index0: int = 0,
    train: bool = True,
    threads: int = 4,
    engine: str = "auto",
) -> np.ndarray:
    """Crop + flip: random crop with random hflip when ``train``; a
    deterministic center crop with NO flip otherwise.

    images: [n, H, W, C] uint8 (C-contiguous). index0 is the global index of
    images[0] in the sample stream — it keys the per-image RNG so results
    are reproducible across batch boundaries and engines.
    """
    if images.dtype != np.uint8 or images.ndim != 4:
        raise ValueError(f"expected [n,H,W,C] uint8, got {images.dtype} {images.shape}")
    n, in_h, in_w, ch = images.shape
    out_h, out_w = out_hw
    if out_h > in_h or out_w > in_w:
        raise ValueError(f"crop {out_hw} larger than input {(in_h, in_w)}")
    images = np.ascontiguousarray(images)
    out = np.empty((n, out_h, out_w, ch), np.uint8)

    if engine not in ("auto", "native", "python"):
        raise ValueError(f"unknown engine {engine!r}")
    lib = _native_lib() if engine in ("auto", "native") else None
    if engine == "native" and lib is None:
        raise NativeBuildError("native augment engine unavailable")
    if lib is not None:
        rc = lib.aug_batch(
            images.ctypes.data_as(ctypes.c_char_p),
            out.ctypes.data_as(ctypes.c_char_p),
            n, in_h, in_w, ch, out_h, out_w, seed, index0,
            int(train), threads,
        )
        if rc != 0:
            raise ValueError(f"aug_batch failed with rc={rc}")
        return out

    for i in range(n):
        y, x, flip = _decisions(seed, index0 + i, in_h - out_h, in_w - out_w, train)
        crop = images[i, y:y + out_h, x:x + out_w]
        out[i] = crop[:, ::-1] if flip else crop
    return out
