"""Native (C++) runtime components, built on demand with the system g++.

The compiled artifacts are content-addressed under ``_build/`` next to the
sources; a missing toolchain or failed compile degrades gracefully — every
native component has a pure-Python fallback chosen by its Python wrapper
(see native/pipeline.py).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()
_CACHE: dict[str, ctypes.CDLL | None] = {}


class NativeBuildError(RuntimeError):
    pass


def _source_digest(src_path: str) -> str:
    with open(src_path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def load_library(source: str, *, cxxflags: tuple[str, ...] = ()) -> ctypes.CDLL:
    """Compile (if needed) and dlopen a one-file C++ library.

    ``source`` is a filename relative to this package. The .so is keyed by a
    digest of the source, so edits rebuild automatically and stale binaries
    are never loaded.
    """
    src_path = os.path.join(_DIR, source)
    key = f"{source}:{_source_digest(src_path)}"
    with _LOCK:
        if key in _CACHE:
            lib = _CACHE[key]
            if lib is None:
                raise NativeBuildError(f"previous build of {source} failed")
            return lib
        so_path = os.path.join(
            _BUILD_DIR, f"{os.path.splitext(source)[0]}-{key.split(':')[1]}.so"
        )
        if not os.path.exists(so_path):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            # Unique tmp per process: concurrent builders (test workers,
            # executor replicas) must not interleave writes into one file;
            # os.replace publishes whole .so files atomically, last wins.
            fd, tmp = tempfile.mkstemp(
                dir=_BUILD_DIR, suffix=".so.tmp"
            )
            os.close(fd)
            cmd = [
                "g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
                *cxxflags, src_path, "-o", tmp,
            ]
            try:
                try:
                    # lint: ok blocking-under-lock — one-shot compile-cache fill; serializing the g++ build is this lock's purpose
                    proc = subprocess.run(
                        cmd, capture_output=True, text=True, timeout=120
                    )
                except (OSError, subprocess.TimeoutExpired) as e:
                    _CACHE[key] = None
                    raise NativeBuildError(f"g++ unavailable: {e}") from e
                if proc.returncode != 0:
                    _CACHE[key] = None
                    raise NativeBuildError(
                        f"compile failed for {source}:\n{proc.stderr[-4000:]}"
                    )
                os.replace(tmp, so_path)
            finally:
                # Failed/timed-out builds must not litter _build/ with
                # .so.tmp files (success os.replace()s the tmp away).
                if os.path.exists(tmp):
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
        try:
            lib = ctypes.CDLL(so_path)
        except OSError as e:
            # Corrupt or wrong-arch binary: report as a build problem so
            # engine="auto" callers fall back instead of crashing.
            _CACHE[key] = None
            raise NativeBuildError(f"dlopen failed for {so_path}: {e}") from e
        _CACHE[key] = lib
        return lib
