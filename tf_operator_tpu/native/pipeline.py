"""Record pipeline: ctypes binding for the C++ loader + Python fallback.

``RecordPipeline`` streams batches of fixed-size records from a binary file
with per-epoch shuffling and multi-threaded prefetch. The native engine
(record_pipeline.cc) does the IO and shuffling off the GIL; the pure-Python
engine implements identical semantics (same splitmix64 shuffle, same batch
order) for environments without a toolchain — engines are interchangeable
and the tests assert batch-for-batch equivalence.
"""

from __future__ import annotations

import ctypes
import os
import threading
import queue as queue_mod
from typing import Iterator

import numpy as np

from tf_operator_tpu.native import NativeBuildError, load_library
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="native-pipeline")

_MASK = (1 << 64) - 1


def _splitmix64_stream(seed: int) -> Iterator[int]:
    s = (seed ^ 0x9E3779B97F4A7C15) & _MASK
    while True:
        s = (s + 0x9E3779B97F4A7C15) & _MASK
        z = s
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
        yield (z ^ (z >> 31)) & _MASK


def epoch_order(num_records: int, seed: int, epoch: int,
                shuffle: bool, shard_id: int = 0,
                num_shards: int = 1, engine: str = "auto") -> np.ndarray:
    """The record order for one epoch — shared by both engines. With
    sharding, every shard computes the SAME global order and takes its
    strided slice TRUNCATED to the common floor(n / num_shards) length:
    shards are disjoint and all exactly the same size (lockstep hosts see
    the same batch count and sizes — the multi-process shard_batch
    contract); the <num_shards remainder records of an epoch are dropped
    and re-dealt by the next epoch's shuffle, so nothing is systematically
    lost.

    engine="auto" runs the shuffle in C (dp_epoch_order; the interpreter's
    Fisher-Yates loop is ~1000x slower at million-record scale), falling
    back to Python. engine="python" is the bit-identical oracle the native
    tests compare against."""
    if engine == "auto":
        native = _native_epoch_order(
            num_records, seed, epoch, shuffle, shard_id, num_shards
        )
        if native is not None:
            return native
    order = np.arange(num_records, dtype=np.uint64)
    if shuffle and num_records > 1:
        rng = _splitmix64_stream(seed * 1000003 + epoch)
        for i in range(num_records - 1, 0, -1):
            j = next(rng) % (i + 1)
            order[i], order[j] = order[j], order[i]
    if num_shards > 1:
        order = order[shard_id::num_shards][: num_records // num_shards]
    return order


def _native_epoch_order(num_records: int, seed: int, epoch: int,
                        shuffle: bool, shard_id: int,
                        num_shards: int) -> np.ndarray | None:
    try:
        lib = load_library("record_pipeline.cc")
    except NativeBuildError:
        return None
    if not hasattr(lib, "dp_epoch_order"):
        return None
    lib.dp_epoch_order.restype = ctypes.c_int64
    lib.dp_epoch_order.argtypes = [
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_uint64, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
    ]
    keep = num_records // num_shards if num_shards > 1 else num_records
    out = np.empty(keep, dtype=np.uint64)
    n = lib.dp_epoch_order(
        num_records, seed, epoch, int(shuffle), shard_id, num_shards,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), keep,
    )
    if n < 0 or n != keep:
        return None
    return out


class _NativeEngine:
    def __init__(self, path: str, record_bytes: int, batch: int,
                 prefetch: int, threads: int, seed: int,
                 shuffle: bool, loop: bool, shard_id: int,
                 num_shards: int) -> None:
        lib = load_library("record_pipeline.cc")
        lib.dp_open.restype = ctypes.c_void_p
        lib.dp_open.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.dp_next.restype = ctypes.c_int64
        lib.dp_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.dp_close.argtypes = [ctypes.c_void_p]
        lib.dp_num_records.restype = ctypes.c_uint64
        lib.dp_num_records.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._record_bytes = record_bytes
        self._batch = batch
        self._handle = lib.dp_open(
            path.encode(), record_bytes, batch, prefetch, threads, seed,
            int(shuffle), int(loop), shard_id, num_shards,
        )
        if not self._handle:
            raise NativeBuildError(f"dp_open failed for {path}")
        self.num_records = int(lib.dp_num_records(self._handle))

    def next(self) -> np.ndarray | None:
        # dp_next writes straight into the returned array's memory — no
        # intermediate ctypes buffer. The previous create_string_buffer +
        # .raw + slice + .copy() chain made THREE extra copies of every
        # batch (~250 MB of memcpy per 62 MB batch at bench shapes), which
        # capped the measured single-core loader at ~2.2k img/s.
        out = np.empty((self._batch, self._record_bytes), np.uint8)
        n = self._lib.dp_next(
            self._handle, out.ctypes.data_as(ctypes.c_char_p), out.nbytes
        )
        if n == 0:
            return None
        if n < 0:
            raise IOError("native record pipeline read error")
        return out if n == self._batch else out[:n]

    def close(self) -> None:
        if self._handle:
            self._lib.dp_close(self._handle)
            self._handle = None


class _PythonEngine:
    """Same semantics, implemented with reader threads + a bounded queue."""

    def __init__(self, path: str, record_bytes: int, batch: int,
                 prefetch: int, threads: int, seed: int,
                 shuffle: bool, loop: bool, shard_id: int,
                 num_shards: int) -> None:
        size = os.path.getsize(path)
        if size == 0 or size % record_bytes:
            raise ValueError(f"{path}: size {size} not a multiple of record")
        self.num_records = size // record_bytes
        # Empty-shard validation lives in RecordPipeline.__init__ (shared
        # by both engines).
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=max(1, prefetch))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce,
            args=(path, record_bytes, batch, seed, shuffle, loop,
                  shard_id, num_shards),
            daemon=True,
        )
        self._thread.start()

    def _produce(self, path, record_bytes, batch, seed, shuffle, loop,
                 shard_id, num_shards):
        try:
            epoch = 0
            with open(path, "rb") as f:
                while not self._stop.is_set():
                    order = epoch_order(self.num_records, seed, epoch,
                                        shuffle, shard_id, num_shards)
                    for lo in range(0, len(order), batch):
                        recs = order[lo: lo + batch]
                        out = np.empty((len(recs), record_bytes), np.uint8)
                        for i, r in enumerate(recs):
                            f.seek(int(r) * record_bytes)
                            out[i] = np.frombuffer(
                                f.read(record_bytes), np.uint8
                            )
                        if not self._put(out):
                            return
                    if not loop:
                        self._put(None)
                        return
                    epoch += 1
        except Exception as exc:  # noqa: BLE001 — surfaced to the consumer
            # Mirror the native engine's error contract (dp_next -> -1):
            # a producer fault must raise in next(), never hang it.
            self._put(exc)

    def _put(self, item) -> bool:
        """Bounded put that honors stop; False when stopping."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue_mod.Full:
                continue
        return False

    def next(self) -> np.ndarray | None:
        item = self._q.get()
        if isinstance(item, Exception):
            raise IOError("record pipeline producer failed") from item
        return item

    def close(self) -> None:
        self._stop.set()
        # Sentinel for a reader concurrently blocked in next()'s get(): the
        # producer exits via _put returning False without putting anything,
        # so without this a reader thread would hang forever. Drain-then-put
        # must loop: a producer blocked in _put can deposit one more real
        # item right after a drain pass (refilling a size-1 queue), in which
        # case the first put_nowait raises Full and must be retried — the
        # producer stops refilling once it observes _stop, so this converges.
        while True:
            try:
                while True:
                    self._q.get_nowait()
            except queue_mod.Empty:
                pass
            try:
                self._q.put_nowait(None)
                return
            except queue_mod.Full:
                continue


class RecordPipeline:
    """Batched, shuffled, prefetching reader over fixed-size records.

    engine: "native" (C++), "python", or "auto" (native with fallback).
    Iterating yields [n, record_bytes] uint8 arrays (the final batch of an
    epoch may be short); callers reinterpret via .view(dtype).reshape(...).

    shard_id/num_shards: multi-host input — every shard computes the same
    per-epoch order and consumes its strided slice, so shards are disjoint
    and jointly exhaustive within each epoch (the per-host-input contract
    of shard_batch's multi-process path).
    """

    def __init__(self, path: str, record_bytes: int, batch: int, *,
                 prefetch: int = 4, threads: int = 2, seed: int = 0,
                 shuffle: bool = True, loop: bool = False,
                 engine: str = "auto", shard_id: int = 0,
                 num_shards: int = 1) -> None:
        if num_shards < 1 or not 0 <= shard_id < num_shards:
            raise ValueError(f"bad shard {shard_id}/{num_shards}")
        # Data-configuration errors surface HERE, not as a fake
        # native-build failure from dp_open returning null.
        total = os.path.getsize(path) // record_bytes if os.path.exists(path) else 0
        if total and total // num_shards == 0:
            raise ValueError(
                f"shard {shard_id}/{num_shards} is empty: only {total} "
                f"records (equal-size shards get n // num_shards each)"
            )
        args = (path, record_bytes, batch, prefetch, threads, seed, shuffle,
                loop, shard_id, num_shards)
        if engine == "native":
            self._engine = _NativeEngine(*args)
        elif engine == "python":
            self._engine = _PythonEngine(*args)
        elif engine == "auto":
            try:
                self._engine = _NativeEngine(*args)
            except NativeBuildError as e:
                LOG.warning("native pipeline unavailable (%s); python engine", e)
                self._engine = _PythonEngine(*args)
        else:
            raise ValueError(f"unknown engine {engine!r}")
        self.engine_name = type(self._engine).__name__.strip("_")

    @property
    def num_records(self) -> int:
        return self._engine.num_records

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            out = self._engine.next()
            if out is None:
                return
            yield out

    def close(self) -> None:
        self._engine.close()

    def __enter__(self) -> "RecordPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def write_records(path: str, array: np.ndarray) -> None:
    """Write an [n, ...] array as n fixed-size records (row-major bytes)."""
    arr = np.ascontiguousarray(array)
    with open(path, "wb") as f:
        f.write(arr.tobytes())


class MMapRecordPipeline:
    """Zero-copy record access for page-cache-resident files: the file is
    mmap'd once and batches are INDEX arrays (epoch_order slices), consumed
    by ``augment.augment_gather`` which crops straight out of the mapping —
    the only host byte movement per image is the crop write itself. On a
    single-core host this roughly 5x's the pread-ring loader at bench
    shapes (~3.3k -> ~16k img/s, 256^2 records -> 224^2 crops).

    Same epoch/shuffle/shard semantics as RecordPipeline (both ride
    epoch_order), so swapping pipelines never changes the sample stream.
    Use RecordPipeline when records must be materialized as arrays (cold
    storage, transforms that need contiguous batches); use this when the
    consumer can gather (augment_gather / fancy indexing).
    """

    def __init__(self, path: str, record_bytes: int, batch: int, *,
                 seed: int = 0, shuffle: bool = True, loop: bool = False,
                 shard_id: int = 0, num_shards: int = 1) -> None:
        if num_shards < 1 or not 0 <= shard_id < num_shards:
            raise ValueError(f"bad shard {shard_id}/{num_shards}")
        size = os.path.getsize(path)
        if size == 0 or size % record_bytes:
            raise ValueError(
                f"{path}: size {size} not a multiple of record_bytes "
                f"{record_bytes}"
            )
        self.data = np.memmap(path, np.uint8, mode="r")
        self.record_bytes = record_bytes
        self.num_records = size // record_bytes
        if self.num_records // num_shards == 0:
            raise ValueError(
                f"shard {shard_id}/{num_shards} is empty: only "
                f"{self.num_records} records"
            )
        self._batch = batch
        self._seed = seed
        self._shuffle = shuffle
        self._loop = loop
        self._shard = (shard_id, num_shards)
        self._epoch = 0
        self._pos = 0
        self._order = epoch_order(
            self.num_records, seed, 0, shuffle, shard_id, num_shards
        )

    def next_indices(self) -> np.ndarray | None:
        """Record indices of the next batch (may be short at epoch end;
        None at EOF when loop=False)."""
        if self._pos >= len(self._order):
            if not self._loop:
                return None
            self._epoch += 1
            self._order = epoch_order(
                self.num_records, self._seed, self._epoch, self._shuffle,
                *self._shard,
            )
            self._pos = 0
        idx = self._order[self._pos:self._pos + self._batch]
        self._pos += len(idx)
        return idx

    def labels(self, indices: np.ndarray, offset: int = -1) -> np.ndarray:
        """Gather one metadata byte per record (default: the trailing label
        byte) as int32."""
        table = np.asarray(self.data).reshape(
            self.num_records, self.record_bytes
        )
        return table[indices, offset].astype(np.int32)

    def close(self) -> None:
        # np.memmap holds the mapping until garbage-collected; explicit
        # close for symmetry with RecordPipeline.
        self.data = None
