"""The fleet router: one HTTP front over N serve replicas.

Routing policy (deliberately boring — the interesting part is what it
reads): pick the ROUTABLE replica with the lowest load score
(probed active slots + probed queue depth + router-local in-flight,
normalized by slot capacity; membership.py), deterministic
lowest-id tie-break. Occupancy and queue depth are exactly the
``tpu_serve_*`` numbers each replica already exports — the router adds
no new instrumentation to the data plane, it just reads the existing
one.

Failure handling is built on PR 7's typed error taxonomy — that is what
``{code, retryable, retry_after_s}`` exists for:

- ``retryable: true`` codes that mean "this replica, not this request"
  (draining / engine_crashed / replica_dead / queue_full / timeout /
  queue_ttl_expired) are retried on a DIFFERENT replica, bounded by
  ``RouterConfig.retries``. ``draining`` marks the replica DRAINING and
  ``replica_dead`` marks it DEAD in the membership table as a side
  effect, so one typed answer deregisters the backend for everyone.
- transport failures (connection refused/reset — the replica vanished
  mid-request) count toward the membership fail threshold and fail over
  the same way.
- non-retryable errors (bad_request, internal) return to the client
  unchanged: retrying a request the replica REJECTED would just burn
  another replica's time.

Every response (success or error) carries ``replica`` (the id that
answered — typed replica-side payloads already self-report it via
serve/resilience.py) and errors carry ``attempts`` so clients and logs
can attribute without reverse-mapping ports.

The transport is injected (``send_fn(replica, body, timeout) ->
(status, payload)``) so the jax-free test tier and the in-process bench
drive the same routing code the HTTP front uses; ``RouterServer`` at the
bottom is the stdlib HTTP wrapper with ``http_send``/``http_probe`` as
the real transport.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Any, Callable

from tf_operator_tpu.fleet.membership import DEAD, FleetMembership, Replica
from tf_operator_tpu.fleet.prefixes import (
    AffinityTable,
    PrefixConfig,
    best_replica,
    hit_blocks,
    holder_of,
    request_digests,
)
from tf_operator_tpu.runtime.metrics import (
    FLEET_PREFIX_HITS,
    FLEET_PREFIX_PULLS,
    FLEET_PREFIX_TOKENS_SAVED,
    FLEET_ROUTER_FAILOVERS,
    FLEET_ROUTER_REQUESTS,
    FLEET_ROUTER_RETRIES,
    FLEET_SHIP_TOTAL,
)
from tf_operator_tpu.runtime.tracing import (
    SERVE_TRACER,
    merge_chrome_traces,
    mint_request_id,
)
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="fleet-router")

# Typed codes that indict the REPLICA, not the request: retry elsewhere.
RETRY_ELSEWHERE = frozenset((
    "draining", "engine_crashed", "replica_dead", "queue_full",
    "queue_ttl_expired", "timeout",
))


@dataclass
class RouterConfig:
    # Additional attempts on OTHER replicas after the first (total sends
    # per request <= retries + 1).
    retries: int = 2
    # Per-send transport timeout handed to send_fn.
    request_timeout_s: float = 300.0
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0


class FleetRouter:
    def __init__(self, membership: FleetMembership,
                 send_fn: Callable[[Replica, dict, float], tuple[int, dict]],
                 config: RouterConfig | None = None, *,
                 prefix: PrefixConfig | None = None,
                 pull_fn: Callable[
                     [Replica, str, float], tuple[int, dict]
                 ] | None = None) -> None:
        self.membership = membership
        self._send = send_fn
        self.cfg = config or RouterConfig()
        # Fleet-global prefix reuse (fleet/prefixes.py): None keeps the
        # PR 9 least-loaded pick byte-for-byte.
        self.prefix_cfg = prefix
        self._pull_fn = pull_fn or http_pull
        self.affinity = AffinityTable(
            prefix.affinity_capacity if prefix else 1
        )
        self._lock = threading.Lock()
        self.requests = 0
        self.retries = 0
        self.failovers = 0
        self.prefix_hits = 0
        self.prefix_tier_hits = 0
        self.prefix_pulls = 0
        self.prefix_pull_misses = 0
        self.prefix_pull_fallbacks = 0
        self.prefix_tokens_saved = 0
        self.affinity_routes = 0

    # -- picking -----------------------------------------------------------

    def pick(self, exclude: frozenset[str] = frozenset(),
             digests: tuple[str, ...] = (),
             session: str = "") -> Replica | None:
        candidates = [
            r for r in self.membership.routable() if r.id not in exclude
        ]
        if not candidates:
            return None
        pfx = self.prefix_cfg
        if pfx is None or not digests:
            return min(candidates, key=lambda r: (r.load, r.id))
        if pfx.session_affinity and session:
            home = self.affinity.home(session)
            if home is not None:
                for r in candidates:
                    # Home is honored only while ROUTABLE (and not
                    # struck out by this request's retry loop): a
                    # draining/dead home simply isn't a candidate, and
                    # the session re-homes through the scored pick.
                    if r.id == home:
                        with self._lock:
                            self.affinity_routes += 1
                        return r
        rep, _ = best_replica(candidates, digests, pfx.weight,
                              pfx.tier_discount)
        return rep

    # -- routing -----------------------------------------------------------

    def route(self, body: dict,
              timeout: float | None = None) -> tuple[int, dict]:
        """Route one /generate body; returns (http_status, payload).
        Never raises for replica-side conditions — everything comes back
        typed, including "no routable replicas" (503, retryable: the
        controller may be replacing a replica right now). Structured-
        decoding fields (``json_schema``/``regex``/``choices``/``stop``/
        ``logprobs``) forward VERBATIM inside the body — grammars
        compile on the replica that serves the request (its compiler
        owns the vocab closure), and the replica's typed
        ``invalid_grammar`` 400 returns unchanged (non-retryable: the
        grammar is bad on every replica)."""
        timeout = timeout or self.cfg.request_timeout_s
        # Mint (or accept) the fleet-wide request id HERE — the router
        # is the first hop; the replica threads it into the scheduler's
        # spans, and the merged trace follows it end to end.
        rid = body.get("request_id") or mint_request_id()
        body = dict(body, request_id=rid)
        with self._lock:
            self.requests += 1
        # Prefix-aware context, computed ONCE per request: the prompt's
        # digest chain (same chained per-block SHA-1 the replicas
        # advertise and the shipped-KV wire format verifies) and the
        # session key for affinity. Single-row prompts only — shipping
        # prefills one row, and multi-row bodies route exactly as the
        # PR 9 pick did.
        pfx = self.prefix_cfg
        digests: tuple[str, ...] = ()
        session = ""
        prompt_len = 0
        if pfx is not None:
            toks = body.get("tokens")
            if (isinstance(toks, list) and len(toks) == 1
                    and isinstance(toks[0], list) and toks[0]):
                digests = request_digests(toks[0], pfx.kv_block)
                prompt_len = len(toks[0])
            session = str(body.get("session") or "")
        exclude: set[str] = set()
        attempts = 0
        last: tuple[int, dict] | None = None
        # (code, replica id) of a retryable answer awaiting a retry —
        # counted only once another replica is actually picked, so
        # tpu_fleet_router_retries_total means what it says ("on a
        # DIFFERENT replica") even in a single-replica fleet.
        pending_retry: tuple[str, str] | None = None
        # ship_failed on a router-pulled shipment retries the SAME
        # replica once, shipment stripped (degrade to local prefill —
        # the replica is healthy, the pulled bytes are what failed).
        retry_same: Replica | None = None
        pull_disabled = False
        while attempts <= self.cfg.retries:
            if retry_same is not None:
                rep, retry_same = retry_same, None
            else:
                rep = self.pick(frozenset(exclude), digests, session)
            if rep is None:
                break
            if pending_retry is not None:
                code, prev_id = pending_retry
                pending_retry = None
                with self._lock:
                    self.retries += 1
                FLEET_ROUTER_RETRIES.inc(code=code or "unknown")
                LOG.info(
                    f"retrying elsewhere after {code} from {prev_id} "
                    f"(attempt {attempts + 1})"
                )
            attempts += 1
            # Prefix pull: the chosen replica misses the request's EXACT
            # whole-prompt digest but another routable replica advertises
            # it — fetch that entry's blocks in the shipped-KV wire
            # format and ride them on the dispatch. Partial-chain hits
            # affect scoring only (the entry table stores whole-prompt
            # entries with their logits; those are what export cleanly).
            attached: dict | None = None
            if (pfx is not None and pfx.pull and digests
                    and not pull_disabled
                    and "shipped_kv" not in body
                    and digests[-1] not in (rep.prefixes or ())
                    # A digest in the chosen replica's OWN host tier
                    # needs no pull either: tier-aware admission
                    # restores it locally (serve/tier.py) — cheaper
                    # than shipping the same bytes over the wire.
                    and digests[-1] not in (rep.tier_prefixes or ())):
                holder = holder_of(
                    self.membership.routable(), digests[-1],
                    exclude | {rep.id},
                )
                if holder is not None:
                    attached = self._pull(holder, digests[-1], rid)
            send_body = body if attached is None else dict(
                body, shipped_kv=attached
            )
            self.membership.begin(rep.id)
            t_send = time.monotonic()
            try:
                status, payload = self._send(rep, send_body, timeout)
            except Exception as exc:  # noqa: BLE001 — transport failure:
                # the replica did not answer at all; it may be mid-death.
                SERVE_TRACER.record(
                    "router.dispatch", t_send, time.monotonic(),
                    request_id=rid, replica=rep.id, attempt=attempts,
                    outcome="transport_error",
                )
                self.membership.probe_failed(rep.id)
                with self._lock:
                    self.failovers += 1
                FLEET_ROUTER_FAILOVERS.inc()
                LOG.warning(
                    f"replica {rep.id} unreachable ({exc!r}); failing over"
                )
                exclude.add(rep.id)
                last = (503, {
                    "error": f"replica unreachable: {exc!r}",
                    "code": "replica_unreachable", "retryable": True,
                    "replica": rep.id, "request_id": rid,
                })
                continue
            finally:
                self.membership.end(rep.id)
            payload = dict(payload)
            payload.setdefault("replica", rep.id)
            payload.setdefault("request_id", rid)
            SERVE_TRACER.record(
                "router.dispatch", t_send, time.monotonic(),
                request_id=rid, replica=rep.id, attempt=attempts,
                status=status, code=payload.get("code", ""),
            )
            if status < 400:
                FLEET_ROUTER_REQUESTS.inc(outcome="ok")
                self._note_prefix_success(
                    rep, digests, prompt_len, attached, session
                )
                return status, payload
            code = payload.get("code", "")
            # Membership side effects come FIRST: even when the retry
            # budget is spent, a typed draining/dead answer must still
            # deregister the backend.
            if code == "replica_dead":
                self.membership.mark_dead(rep.id)
            elif code == "draining":
                self.membership.mark_draining(rep.id)
            if code == "ship_failed" and attached is not None:
                # The PULLED bytes failed replica-side verification
                # (stale export, geometry drift) — the replica itself is
                # healthy, so degrade to local prefill THERE: same
                # replica, shipment stripped, pulls off for the rest of
                # this request. Consumes an attempt, so the loop stays
                # bounded.
                with self._lock:
                    self.prefix_pull_fallbacks += 1
                FLEET_PREFIX_PULLS.inc(outcome="ship_failed")
                LOG.warning(
                    f"pulled prefix rejected by {rep.id} (ship_failed); "
                    "retrying there with local prefill"
                )
                pull_disabled = True
                retry_same = rep
                last = (status, payload)
                continue
            if not (payload.get("retryable") and code in RETRY_ELSEWHERE):
                FLEET_ROUTER_REQUESTS.inc(outcome="typed")
                return status, payload
            last = (status, payload)
            exclude.add(rep.id)
            pending_retry = (code, rep.id)
        if last is not None:
            status, payload = last
            payload["attempts"] = attempts
            FLEET_ROUTER_REQUESTS.inc(
                outcome="transport"
                if payload.get("code") == "replica_unreachable" else "typed"
            )
            return status, payload
        FLEET_ROUTER_REQUESTS.inc(outcome="no_replica")
        # Demand with nowhere to go — the scale-from-zero signal the
        # autoscaler reads via membership.take_unrouted().
        self.membership.note_unrouted()
        return 503, {
            "error": "no routable replicas",
            "code": "no_replica", "retryable": True, "retry_after_s": 1.0,
            "attempts": attempts, "request_id": rid,
        }

    # -- prefix reuse ------------------------------------------------------

    def _pull(self, holder: Replica, digest: str,
              rid: str) -> dict | None:
        """Fetch ``digest``'s exported shipment from ``holder``
        (GET /prefix/<digest>). Returns the shipment payload or None —
        EVERY failure mode (typed prefix_not_found from a stale
        advertisement, transport error, malformed answer) degrades to
        local prefill at the chosen replica; a pull never fails the
        request."""
        t0 = time.monotonic()
        try:
            status, payload = self._pull_fn(
                holder, digest, self.prefix_cfg.pull_timeout_s
            )
        except Exception as exc:  # noqa: BLE001 — holder unreachable:
            # it may be mid-death; the prober will notice. Degrade.
            SERVE_TRACER.record(
                "prefix.pull", t0, time.monotonic(),
                request_id=rid, holder=holder.id,
                outcome="transport_error",
            )
            with self._lock:
                self.prefix_pull_misses += 1
            FLEET_PREFIX_PULLS.inc(outcome="transport_error")
            LOG.warning(
                f"prefix pull from {holder.id} failed ({exc!r}); "
                "degrading to local prefill"
            )
            return None
        shipment = payload.get("shipment") if status < 400 else None
        SERVE_TRACER.record(
            "prefix.pull", t0, time.monotonic(),
            request_id=rid, holder=holder.id, status=status,
            outcome="ok" if shipment else
            (payload.get("code") or "error"),
        )
        if shipment:
            with self._lock:
                self.prefix_pulls += 1
            FLEET_PREFIX_PULLS.inc(outcome="ok")
            return shipment
        # Typed miss — usually prefix_not_found, the advertisement
        # raced the holder's LRU. The holder is fine; just prefill.
        with self._lock:
            self.prefix_pull_misses += 1
        FLEET_PREFIX_PULLS.inc(
            outcome=payload.get("code") or "error"
        )
        return None

    def _note_prefix_success(self, rep: Replica,
                             digests: tuple[str, ...], prompt_len: int,
                             attached: dict | None,
                             session: str) -> None:
        """Success-path prefix bookkeeping: hit/saved counters and the
        session's new home. tokens_saved is the ROUTER'S estimate of
        prefill work avoided — exact-chain hits and pulls save the whole
        prompt, partial hits save the covered whole blocks (the replica
        side's kv_prefill_tokens_saved is the ground truth; this one
        exists so the fleet number needs no replica scrape)."""
        pfx = self.prefix_cfg
        if pfx is None or not digests:
            return
        saved = 0
        if attached is not None:
            # Pulled the exact whole-prompt entry: lands as a
            # table-insert join, the whole prefill avoided.
            saved = prompt_len
        else:
            hit = hit_blocks(digests, rep.prefixes or ())
            if hit:
                with self._lock:
                    self.prefix_hits += 1
                FLEET_PREFIX_HITS.inc()
                saved = prompt_len if hit == len(digests) \
                    else hit * pfx.kv_block
            elif hit_blocks(digests, rep.tier_prefixes or ()):
                # Warm-tier hit (serve/tier.py): the replica restores
                # the prefix from its host tier at admission — prefill
                # compute saved, counted apart from hot hits (saved
                # tokens stay the replica side's story: the router
                # cannot know how deep the restore actually landed).
                with self._lock:
                    self.prefix_tier_hits += 1
        if saved:
            with self._lock:
                self.prefix_tokens_saved += saved
            FLEET_PREFIX_TOKENS_SAVED.inc(saved)
        if pfx.session_affinity and session:
            # SUCCESS only: a failed dispatch must not re-home the
            # session onto the replica that just failed it.
            self.affinity.set_home(session, rep.id)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            snap = {
                "requests": self.requests,
                "retries": self.retries,
                "failovers": self.failovers,
                "retry_budget": self.cfg.retries,
            }
            if self.prefix_cfg is not None:
                snap["prefix"] = {
                    "hits": self.prefix_hits,
                    "tier_hits": self.prefix_tier_hits,
                    "pulls": self.prefix_pulls,
                    "pull_misses": self.prefix_pull_misses,
                    "pull_fallbacks": self.prefix_pull_fallbacks,
                    "tokens_saved": self.prefix_tokens_saved,
                    "affinity_routes": self.affinity_routes,
                    "weight": self.prefix_cfg.weight,
                    "tier_discount": self.prefix_cfg.tier_discount,
                    "kv_block": self.prefix_cfg.kv_block,
                    "affinity": self.affinity.snapshot(),
                }
        return snap


# ---------------------------------------------------------------------------
# HTTP transport + front
# ---------------------------------------------------------------------------


def _http_post_json(url: str, body: dict,
                    timeout: float) -> tuple[int, dict]:
    """ONE wire implementation for the replica-facing POSTs: typed
    error bodies come back as (status, payload) rather than raising —
    only transport-level failures raise (and trigger failover)."""
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {"error": str(e), "code": "internal",
                       "retryable": False}
        return e.code, payload


def http_send(rep: Replica, body: dict, timeout: float) -> tuple[int, dict]:
    """POST the body to the replica's /generate."""
    return _http_post_json(f"http://{rep.endpoint}/generate", body,
                           timeout)


def http_ship(rep: Replica, body: dict, timeout: float) -> tuple[int, dict]:
    """POST a prompt to a PREFILL replica's /prefill (serve/disagg.py
    PrefillServer) — the two-stage dispatch's stage-1 transport."""
    return _http_post_json(f"http://{rep.endpoint}/prefill", body,
                           timeout)


def http_pull(rep: Replica, digest: str,
              timeout: float) -> tuple[int, dict]:
    """GET the holder's /prefix/<digest> (fleet/replica.py): 200 with
    ``{"shipment": <wire payload>}`` or a typed error body — the stale
    advertisement race answers ``prefix_not_found`` (404), which the
    router degrades to local prefill. Only transport failures raise."""
    try:
        with urllib.request.urlopen(
            f"http://{rep.endpoint}/prefix/{digest}", timeout=timeout
        ) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {"error": str(e), "code": "internal",
                       "retryable": False}
        return e.code, payload


def http_probe(endpoint: str, timeout: float = 2.0) -> dict:
    with urllib.request.urlopen(
        f"http://{endpoint}/healthz", timeout=timeout
    ) as resp:
        return json.loads(resp.read() or b"{}")


def http_fetch_traces(endpoint: str, timeout: float = 3.0) -> dict:
    """GET one serve surface's /debug/traces (a catapult document with
    the ``epochUnixUs`` merge metadata)."""
    with urllib.request.urlopen(
        f"http://{endpoint}/debug/traces", timeout=timeout
    ) as resp:
        return json.loads(resp.read() or b"{}")


def merged_fleet_traces(membership: FleetMembership,
                        fetch_fn: Callable[[str], dict] = http_fetch_traces,
                        *, router_doc: dict | None = None) -> dict:
    """THE fleet-trace merge: the router's own ring plus every known
    replica's /debug/traces, rebased onto one timeline and keyed by the
    ``request_id`` span attribute (dead replicas are skipped silently —
    their process is gone, their spans live on in the ring they already
    shipped... nowhere; the router-side dispatch spans still tell the
    failover story). Shared by RouterServer's /debug/traces and
    ``tpuctl trace``."""
    from concurrent.futures import ThreadPoolExecutor

    docs: list[tuple[str, dict]] = [
        ("router", router_doc if router_doc is not None
         else SERVE_TRACER.export_doc())
    ]
    live = [rep for rep in membership.all() if rep.state != DEAD]
    if live:
        # Concurrent fetch, the PR 9 probe-sweep rule: one wedged
        # (non-DEAD) replica must not stall the handler for its whole
        # timeout times the fleet size.
        def fetch(rep):
            try:
                return f"replica:{rep.id}", fetch_fn(rep.endpoint)
            except Exception:  # noqa: BLE001 — a probe-sized best
                # effort; an unreachable replica must not fail the
                # whole merge.
                return None
        with ThreadPoolExecutor(min(8, len(live))) as pool:
            docs.extend(d for d in pool.map(fetch, live) if d)
    return merge_chrome_traces(docs)


class RouterServer:
    """The stdlib HTTP front: /generate forwarded through the router,
    /healthz the fleet aggregate (ok while anything is routable),
    /debug/fleet the membership+router snapshot, /metrics the registry.
    A background prober keeps membership fresh."""

    def __init__(self, membership: FleetMembership, *,
                 router: FleetRouter | None = None,
                 config: RouterConfig | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 probe_fn: Callable[[str], dict] | None = None,
                 trace_fn: Callable[[str], dict] | None = None,
                 extra_debug: Callable[[], dict] | None = None,
                 prefix: PrefixConfig | None = None) -> None:
        from http.server import ThreadingHTTPServer

        from tf_operator_tpu.serve.httpapi import QuietHandler

        self.membership = membership
        cfg = config or RouterConfig()
        self.router = router or FleetRouter(membership, http_send, cfg,
                                            prefix=prefix)
        self.cfg = cfg
        self._probe_fn = probe_fn or (
            lambda ep: http_probe(ep, cfg.probe_timeout_s)
        )
        self._trace_fn = trace_fn or http_fetch_traces
        self._extra_debug = extra_debug
        self._stop = threading.Event()
        outer = self

        class Handler(QuietHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    self.send_json(200, outer.healthz_payload())
                elif path == "/debug/fleet":
                    self.send_json(200, outer.debug_snapshot())
                elif path == "/debug/traces":
                    # The FLEET timeline: router dispatch spans merged
                    # with every live replica's ring, one pid per
                    # source, rebased to one clock — filter on a
                    # request_id arg in ui.perfetto.dev to follow one
                    # request across the hop.
                    self.send_json(200, outer.merged_traces())
                elif path == "/metrics":
                    self.send_metrics()
                else:
                    self.send_json(404, {"error": "unknown path"})

            def do_POST(self):
                if self.path.split("?", 1)[0] != "/generate":
                    self.send_json(404, {"error": "unknown path"})
                    return
                try:
                    body = self.read_json_body()
                except ValueError:
                    self.send_json(400, {"error": "bad JSON",
                                         "code": "bad_request",
                                         "retryable": False})
                    return
                # X-Request-Id is the client-facing spelling; the body
                # field is the wire spelling the fleet uses internally.
                rid = self.headers.get("X-Request-Id")
                if rid and not body.get("request_id"):
                    body["request_id"] = rid
                status, payload = outer.router.route(body)
                self.send_json(status, payload)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._threads: list[threading.Thread] = []

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def healthz_payload(self) -> dict:
        counts = self.membership.counts()
        return {
            "ok": counts["ready"] > 0,
            "router": True,
            "replicas": counts,
        }

    def debug_snapshot(self) -> dict:
        snap = {
            "membership": self.membership.snapshot(),
            "router": self.router.snapshot(),
            # The fleet-wide prefix directory roll-up (how many distinct
            # digests are advertised, by how many replicas) — the
            # per-replica lists stay in membership.snapshot() as counts.
            "prefixes": self.membership.prefix_directory(),
        }
        if self._extra_debug is not None:
            snap.update(self._extra_debug())
        return snap

    def merged_traces(self) -> dict:
        return merged_fleet_traces(self.membership, self._trace_fn)

    def start(self) -> "RouterServer":
        serve = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="fleet-router",
        )
        serve.start()
        probe = threading.Thread(
            target=self._probe_loop, daemon=True, name="fleet-prober"
        )
        probe.start()
        self._threads = [serve, probe]
        LOG.info(f"router listening on {self.endpoint}")
        return self

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.cfg.probe_interval_s):
            self.membership.probe(self._probe_fn)

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()


# ---------------------------------------------------------------------------
# Two-stage dispatch: prefill pool -> decode pool (disaggregated serving)
# ---------------------------------------------------------------------------


@dataclass
class DisaggConfig:
    """Knobs of the two-stage dispatch. ``ship_min_tokens`` gates which
    prompts are worth the hop: tiny prompts prefill in one decode-loop
    iteration and shipping them only adds wire latency — the
    interference win is the LONG prefills. 0 ships everything (the
    deterministic test/bench setting)."""

    ship_min_tokens: int = 0
    # One fresh prefill->decode cycle after a decode replica answers
    # ship_failed before giving up on shipping and going local.
    reship_retries: int = 1


class DisaggRouter:
    """Two-stage dispatch over TWO pools: route the prompt to the
    least-loaded PREFILL replica (/prefill → the shipped-KV payload),
    attach the shipment, then route to the least-loaded DECODE replica
    (/generate). Each stage is a full PR 9 ``FleetRouter`` — the typed
    retry-elsewhere contract, membership side effects, and transport
    failover all apply per pool unchanged.

    Failure policy (every path ends in a served request):

    - prefill pool EMPTY (``no_replica``) → typed ``prefill_pool_empty``
      noted on the response, decode pool prefills locally — a dead
      prefill pool degrades to exactly the time-shared engine;
    - prefill stage exhausts its retry budget (typed/transport) →
      local-prefill fallback the same way;
    - prefill rejects the REQUEST (``bad_request``) → returned to the
      client unchanged (the decode pool would reject it identically);
    - decode replica answers ``ship_failed`` (digest/geometry mismatch)
      → ONE fresh prefill→decode cycle (``reship_retries``), then
      local-prefill fallback. Never the same bytes to another decode
      replica: the payload is what failed.
    """

    def __init__(self, prefill_membership: FleetMembership,
                 decode_membership: FleetMembership, *,
                 prefill_send: Callable[..., tuple[int, dict]] = http_ship,
                 decode_send: Callable[..., tuple[int, dict]] = http_send,
                 config: RouterConfig | None = None,
                 disagg: DisaggConfig | None = None) -> None:
        cfg = config or RouterConfig()
        self.cfg = cfg
        self.disagg = disagg or DisaggConfig()
        self.prefill = FleetRouter(prefill_membership, prefill_send, cfg)
        self.decode = FleetRouter(decode_membership, decode_send, cfg)
        self._lock = threading.Lock()
        self.shipped = 0
        self.prefill_pool_empty = 0
        self.local_fallbacks = 0
        self.ship_failures = 0

    def _note(self, counter: str, outcome: str) -> None:
        with self._lock:
            setattr(self, counter, getattr(self, counter) + 1)
        FLEET_SHIP_TOTAL.inc(outcome=outcome)

    def _stage_prefill(self, body: dict, rid: str,
                       timeout: float | None) -> tuple[
            dict | None, str | None, dict | None]:
        """Run stage 1. Returns (shipment payload | None, note,
        reject): ``reject`` is the prefill pool's own typed
        ``bad_request`` answer — route() returns it to the client
        verbatim (the prompt itself is malformed; the replica's error
        detail must not be replaced with a generic string)."""
        t0 = time.monotonic()
        status, payload = self.prefill.route(
            {"tokens": body["tokens"], "request_id": rid},
            timeout=timeout,
        )
        SERVE_TRACER.record(
            "kv.ship", t0, time.monotonic(),
            request_id=rid, stage="prefill_dispatch", status=status,
            code=payload.get("code", ""),
            replica=payload.get("replica", ""),
        )
        if status < 400 and payload.get("shipped_kv"):
            self._note("shipped", "shipped")
            return payload["shipped_kv"], "shipped", None
        code = payload.get("code", "")
        if code == "no_replica":
            # The pool is empty/unroutable: typed degradation, decode
            # prefills locally.
            self._note("prefill_pool_empty", "prefill_pool_empty")
            return None, "prefill_pool_empty", None
        if code == "bad_request":
            return None, None, payload
        self._note("local_fallbacks", "local_fallback")
        return None, code or "prefill_failed", None

    def route(self, body: dict,
              timeout: float | None = None) -> tuple[int, dict]:
        rid = body.get("request_id") or mint_request_id()
        body = dict(body, request_id=rid)
        # The disagg router reads the prompt itself (the ship-gate and
        # the stage-1 body), so malformed tokens must 400 typed HERE —
        # the plain router can leave that to the replica, this one
        # would crash the handler instead.
        prompt = body.get("tokens")
        if (not isinstance(prompt, list) or not prompt
                or not isinstance(prompt[0], list)):
            return 400, {
                "error": "tokens must be [[...]] (one prompt row)",
                "code": "bad_request", "retryable": False,
                "request_id": rid,
            }
        prompt_len = len(prompt[0])
        ship_note: str | None = None
        attempts = self.disagg.reship_retries + 1
        for attempt in range(attempts):
            shipped, note = None, None
            # Ship single-row long prompts only: a shipment prefills
            # ONE prompt, and multi-row bodies must behave exactly as
            # they do through the plain router (the decode replica
            # decides what to do with the extra rows — no annotation).
            if len(prompt) == 1:
                note = "below_min_tokens"
                if prompt_len >= self.disagg.ship_min_tokens:
                    # The caller's bound covers BOTH stages.
                    shipped, note, reject = self._stage_prefill(
                        body, rid, timeout
                    )
                    if reject is not None:
                        # The prefill pool's own typed bad_request: the
                        # prompt itself is malformed — hand the
                        # replica's answer (detail included) straight
                        # back.
                        reject.setdefault("request_id", rid)
                        return 400, reject
            ship_note = note
            decode_body = dict(body)
            if shipped is not None:
                decode_body["shipped_kv"] = shipped
            status, payload = self.decode.route(decode_body,
                                                timeout=timeout)
            if (payload.get("code") == "ship_failed"
                    and attempt + 1 < attempts):
                # The payload is what failed — re-run the PREFILL stage
                # for fresh bytes rather than burning decode replicas.
                self._note("ship_failures", "ship_failed")
                continue
            if payload.get("code") == "ship_failed":
                # Budget spent: strip the shipment, decode prefills
                # locally — the request still serves (and the ship
                # annotation must say what actually happened, not that
                # the dropped shipment was used).
                self._note("ship_failures", "ship_failed")
                self._note("local_fallbacks", "local_fallback")
                ship_note = "ship_failed"
                status, payload = self.decode.route(dict(body),
                                                    timeout=timeout)
            if ship_note and status < 400:
                payload = dict(payload, ship=ship_note)
            return status, payload
        raise AssertionError("unreachable")  # pragma: no cover

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            ship = {
                "shipped": self.shipped,
                "prefill_pool_empty": self.prefill_pool_empty,
                "local_fallbacks": self.local_fallbacks,
                "ship_failures": self.ship_failures,
                "ship_min_tokens": self.disagg.ship_min_tokens,
            }
        return {
            "prefill": self.prefill.snapshot(),
            "decode": self.decode.snapshot(),
            "ship": ship,
        }


class DisaggRouterServer(RouterServer):
    """The stdlib HTTP front of a disaggregated fleet — RouterServer's
    scaffolding (handler, /metrics, /debug routes, lifecycle) with the
    decode pool as ``membership``, the two-stage ``DisaggRouter``
    behind /generate, /healthz aggregating BOTH pools (ok while the
    decode pool is routable — the prefill pool degrades, never gates),
    /debug/fleet carrying per-pool membership, and the probe sweep
    covering both pools each interval."""

    def __init__(self, prefill_membership: FleetMembership,
                 decode_membership: FleetMembership, *,
                 router: DisaggRouter | None = None,
                 config: RouterConfig | None = None,
                 disagg: DisaggConfig | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 probe_fn: Callable[[str], dict] | None = None) -> None:
        cfg = config or RouterConfig()
        self.prefill_membership = prefill_membership
        self.decode_membership = decode_membership
        super().__init__(
            decode_membership,
            router=router or DisaggRouter(
                prefill_membership, decode_membership, config=cfg,
                disagg=disagg,
            ),
            config=cfg, host=host, port=port, probe_fn=probe_fn,
        )

    def healthz_payload(self) -> dict:
        payload = super().healthz_payload()
        payload["disagg"] = True
        payload["prefill_replicas"] = self.prefill_membership.counts()
        return payload

    def debug_snapshot(self) -> dict:
        snap = super().debug_snapshot()
        snap["prefill_membership"] = self.prefill_membership.snapshot()
        return snap

    def merged_traces(self) -> dict:
        """Both pools' rings + the router's own, one timeline: the
        ``kv.ship`` spans bridge the prefill replica's ``prefill.ship``
        to the decode replica's ingest under one request id."""
        doc = merged_fleet_traces(self.decode_membership,
                                  self._trace_fn)
        prefill_docs = []
        for rep in self.prefill_membership.all():
            if rep.state == DEAD:
                continue
            try:
                prefill_docs.append(
                    (f"prefill:{rep.id}", self._trace_fn(rep.endpoint))
                )
            except Exception:  # noqa: BLE001 — best-effort, as in
                # merged_fleet_traces: an unreachable replica must not
                # fail the merge.
                continue
        if prefill_docs:
            doc = merge_chrome_traces([("merged", doc)] + prefill_docs)
        return doc

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.cfg.probe_interval_s):
            self.decode_membership.probe(self._probe_fn)
            self.prefill_membership.probe(self._probe_fn)
