"""/debug/fleet HTTP surface: the TPUServe controller snapshot.

Mounts on the operator's ApiServer via its extra-handler hook (the
/debug/scheduler, /debug/health, /debug/ckpt pattern — see
runtime/observability.mount_observability, which mounts this when the
operator runs with fleet serving on).

    GET /debug/fleet → TPUServeController.debug_snapshot()
                       {fleets: {"ns/name": {target, membership,
                        autoscale}}}

`tpuctl serve` renders this payload; the per-fleet RouterServer exposes
its OWN /debug/fleet (membership + router counters) on the router port —
same name, the fleet seen from two sides.
"""

from __future__ import annotations

import json
from typing import Any

from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="fleet-api")


class FleetDebugHandler:
    def __init__(self, controller: Any) -> None:
        self._controller = controller

    def __call__(self, req: Any) -> bool:
        path = req.path.split("?", 1)[0]
        if req.command != "GET" or path != "/debug/fleet":
            return False
        body = json.dumps(
            self._controller.debug_snapshot(), indent=2
        ).encode()
        req.send_response(200)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)
        return True


def mount_fleet(api_server: Any, controller: Any) -> FleetDebugHandler:
    handler = FleetDebugHandler(controller)
    api_server.add_handler(handler)
    LOG.info("fleet API mounted at /debug/fleet")
    return handler
