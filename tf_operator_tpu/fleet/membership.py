"""Fleet membership: the replica table the router routes from.

One row per serve replica (a TPUServe child job's serving process).
State is derived from the replica's own /healthz — the PR 7/9 readiness
surface — so the table never guesses:

    JOINING   registered, no successful probe yet (not routable)
    READY     ok:true, draining:false, dead:false (routable)
    DRAINING  draining:true (SIGTERM drain in flight) or the controller
              marked it for scale-down — deregistered from routing
              BEFORE the drain completes, so the router never eats the
              drain-window 503s
    CORDONED  operator/health-driven eviction: alive but withdrawn from
              routing (the health machinery is migrating its gang)
    DEAD      dead:true (restart budget exhausted), the controller
              killed it, or ``fail_threshold`` consecutive probe
              failures (the process is gone — connection refused)

Occupancy (active_slots/max_slots) and queue depth ride the same probe
payload (serve_lm /healthz carries them; /debug/serve agrees) and feed
the router's least-loaded pick plus the autoscaler's aggregate signals.
The router also tracks its own in-flight count per replica so a stale
probe cannot stack every request on one replica between sweeps.

Thread-safe; gauges (tpu_fleet_replicas{state}, tpu_fleet_queue_depth)
are re-exported on every mutation/sweep.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable

from tf_operator_tpu.runtime.metrics import (
    FLEET_QUEUE_DEPTH,
    FLEET_REPLICAS,
)
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="fleet-membership")

JOINING = "joining"
READY = "ready"
DRAINING = "draining"
CORDONED = "cordoned"
DEAD = "dead"
STATES = (JOINING, READY, DRAINING, CORDONED, DEAD)


@dataclass
class Replica:
    """One serve replica as the router sees it."""

    id: str
    endpoint: str  # "host:port"
    model_version: str = ""
    state: str = JOINING
    # Disaggregated serving (serve/disagg.py): "decode" replicas take
    # /generate, "prefill" replicas take only /prefill. Pools live in
    # SEPARATE membership tables (a router pick-set must never mix
    # them); the field attributes rows in debug/tpuctl output.
    role: str = "decode"
    # Last probe's load picture (0s until the first successful probe).
    max_slots: int = 0
    active_slots: int = 0
    queue_depth: int = 0
    # SPMD decode width from the probe payload (PR 10): a tp-wide
    # replica is one probe target but many chips — informational for
    # /debug/fleet and capacity math (the least-loaded score already
    # normalizes by max_slots, which is per-REPLICA capacity regardless
    # of how many chips serve it).
    mesh_devices: int = 1
    watchdog_restarts: int = 0
    # Per-replica TTFT p99 from the probe payload (None until a probe
    # carries one) — the autoscaler's latency trigger reads the fleet
    # max so one slow replica is enough to scale.
    ttft_p99_s: float | None = None
    # Per-replica ITL p99 (decode pools): the disaggregation-era decode
    # scale signal — prefill interference and overload show up in
    # inter-token gaps before queues move. Same clear-on-idle contract
    # as ttft_p99_s.
    itl_p99_s: float | None = None
    # Fleet-global prefix reuse (fleet/prefixes.py): the hot prefix
    # digest chain this replica advertised on its last probe — hex
    # chained per-block SHA-1s, MRU first, capped replica-side. The
    # router's prefix-hit scoring and pull-source selection read it.
    prefixes: tuple[str, ...] = ()
    # KV memory hierarchy (serve/tier.py): the WARM host-tier digests
    # this replica advertised — restorable (upload + join), not hot, so
    # the router scores them at a discount (PrefixConfig.tier_discount)
    # and pull-source selection treats them as a second lookup level.
    tier_prefixes: tuple[str, ...] = ()
    # Router-local outstanding requests (begin/end around each send).
    inflight: int = 0
    consecutive_failures: int = 0
    last_probe_at: float | None = None
    registered_at: float = field(default_factory=time.monotonic)

    @property
    def routable(self) -> bool:
        return self.state == READY

    @property
    def load(self) -> float:
        """Least-loaded score: probed backlog plus the router's own
        in-flight count, normalized by capacity (unknown capacity — no
        probe yet — scores as 1 slot so empty newcomers still win)."""
        return (self.active_slots + self.queue_depth + self.inflight) / max(
            1, self.max_slots
        )

    @property
    def occupancy(self) -> float:
        return self.active_slots / max(1, self.max_slots)

    def snapshot(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "endpoint": self.endpoint,
            "state": self.state,
            "role": self.role,
            "modelVersion": self.model_version,
            "maxSlots": self.max_slots,
            "activeSlots": self.active_slots,
            "queueDepth": self.queue_depth,
            "meshDevices": self.mesh_devices,
            "inflight": self.inflight,
            "watchdogRestarts": self.watchdog_restarts,
            "consecutiveFailures": self.consecutive_failures,
            "ttftP99Seconds": self.ttft_p99_s,
            "itlP99Seconds": self.itl_p99_s,
            # Count, not the digest list: /debug/fleet stays readable
            # and digests are opaque outside the router anyway.
            "prefixesAdvertised": len(self.prefixes),
            "tierPrefixesAdvertised": len(self.tier_prefixes),
            "load": round(self.load, 4),
        }


class FleetMembership:
    def __init__(self, *, fail_threshold: int = 3,
                 join_grace_s: float = 120.0,
                 name: str = "default") -> None:
        if fail_threshold < 1:
            raise ValueError("fail_threshold must be >= 1")
        self.fail_threshold = fail_threshold
        self.join_grace_s = join_grace_s
        # Label for the process-global tpu_fleet_* gauges: one operator
        # reconciles many fleets, and unlabeled exports would flip-flop
        # between per-fleet values on every sweep.
        self.name = name
        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}
        # Probe sweeps reuse one pool for the table's lifetime: routers
        # sweep every probe_interval_s (sub-second), and spawning+joining
        # a fresh executor's threads per sweep is pure churn. Workers
        # are created lazily by the executor, so an idle table costs no
        # threads.
        self._probe_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="fleet-probe"
        )
        # Requests the router could not place anywhere (no_replica
        # answers) since the controller last read. This is the ONLY
        # demand signal a scaled-to-zero fleet has: with no replicas
        # there is no queue to measure, so without it minReplicas=0
        # fleets could never scale back up.
        self._unrouted = 0

    # -- registration ------------------------------------------------------

    def register(self, rid: str, endpoint: str, *,
                 model_version: str = "",
                 role: str = "decode") -> Replica:
        """Idempotent: re-registering an existing id only refreshes its
        endpoint/version (the controller calls this every sync)."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                rep = Replica(rid, endpoint, model_version=model_version,
                              role=role)
                self._replicas[rid] = rep
                LOG.info(f"replica {rid} ({role}) registered at "
                         f"{endpoint}")
            else:
                rep.endpoint = endpoint
                if model_version:
                    rep.model_version = model_version
            self._export_locked()
            return rep

    def deregister(self, rid: str) -> None:
        with self._lock:
            if self._replicas.pop(rid, None) is not None:
                LOG.info(f"replica {rid} deregistered")
            self._export_locked()

    def close(self) -> None:
        """Zero this fleet's gauge series before the table is discarded:
        the registry is process-global and set-only, so a deleted
        TPUServe would otherwise keep reporting its last live counts
        (a phantom fleet on dashboards) for the rest of the operator's
        life."""
        with self._lock:
            self._replicas.clear()
            self._export_locked()
        self._probe_pool.shutdown(wait=False)

    # -- probe ingestion ---------------------------------------------------

    def observe(self, rid: str, payload: dict[str, Any]) -> None:
        """Apply one /healthz payload. A cordoned replica stays cordoned
        (the cordon is an external withdrawal, not a health fact); a DEAD
        verdict is sticky until deregistration — the supervisor never
        resurrects a dead replica in place."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            rep.last_probe_at = time.monotonic()
            rep.consecutive_failures = 0
            rep.active_slots = int(payload.get("active_slots", 0))
            rep.queue_depth = int(payload.get("queue_depth", 0))
            rep.max_slots = int(payload.get("max_slots", rep.max_slots))
            rep.mesh_devices = int(
                payload.get("mesh_devices", rep.mesh_devices)
            )
            rep.watchdog_restarts = int(
                payload.get("watchdog_restarts", rep.watchdog_restarts)
            )
            # Absent key = the replica's TTFT window drained (no recent
            # traffic). CLEAR the stale value: latching the last reading
            # would keep the autoscaler's `not ttft_high` scale-down
            # guard tripped forever after any latency episode followed
            # by idle — an idle fleet pinned at max_replicas.
            if payload.get("ttft_p99_s") is not None:
                rep.ttft_p99_s = float(payload["ttft_p99_s"])
            else:
                rep.ttft_p99_s = None
            # Same clear-on-absent contract for the ITL window (the
            # decode pool's latency scale signal).
            if payload.get("itl_p99_s") is not None:
                rep.itl_p99_s = float(payload["itl_p99_s"])
            else:
                rep.itl_p99_s = None
            # Prefix advertisement (fleet/prefixes.py), clear-on-absent
            # too: a replica that freed its last entry stops advertising
            # and must stop attracting prefix-scored traffic.
            rep.prefixes = tuple(
                str(d) for d in (payload.get("prefixes") or ())
            )
            # The warm host-tier advertisement rides the same probe,
            # same clear-on-absent contract (a tier emptied by eviction
            # or --host-tier-bytes 0 must stop attracting discounted
            # prefix traffic).
            rep.tier_prefixes = tuple(
                str(d) for d in (payload.get("tier_prefixes") or ())
            )
            if payload.get("role"):
                rep.role = str(payload["role"])
            if payload.get("dead"):
                self._transition_locked(rep, DEAD)
            elif rep.state == DEAD:
                pass  # sticky (see docstring)
            elif payload.get("draining"):
                self._transition_locked(rep, DRAINING)
            elif rep.state in (CORDONED, DRAINING):
                # External withdrawals are lifted explicitly (uncordon /
                # controller), never by a healthy-looking probe.
                pass
            elif payload.get("ok"):
                self._transition_locked(rep, READY)
            self._export_locked()

    def probe_failed(self, rid: str) -> None:
        """A probe (or a routed send) could not reach the replica at
        all. ``fail_threshold`` consecutive failures = the process is
        gone → DEAD.

        A JOINING replica inside ``join_grace_s`` of registration is
        exempt: the controller registers the endpoint the moment the
        child job exists, but a real replica spends tens of seconds in
        gang admission + jax init before binding its port — counting
        those connection-refusals would declare it DEAD, delete it,
        recreate it at a fresh index, and churn forever without ever
        reaching READY. (An uncordoned replica re-enters JOINING with
        its ORIGINAL registered_at, so a genuinely-gone one still dies
        on schedule.)"""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            if (rep.state == JOINING and time.monotonic()
                    - rep.registered_at < self.join_grace_s):
                return
            rep.consecutive_failures += 1
            if (rep.consecutive_failures >= self.fail_threshold
                    and rep.state != DEAD):
                self._transition_locked(rep, DEAD)
            self._export_locked()

    def probe(self, probe_fn: Callable[[str], dict[str, Any]]) -> None:
        """One sweep: probe_fn(endpoint) -> /healthz dict (raises on an
        unreachable replica). Snapshot the table first — probes do I/O
        and must not run under the lock — and probe CONCURRENTLY: the
        controller runs this on its reconcile path, and a serial sweep
        would let one wedged replica (accepts the connection, never
        answers — the PR 7 stall mode) hold every fleet's autoscale /
        drain / replacement clocks hostage for probe_timeout_s apiece."""
        with self._lock:
            targets = [(r.id, r.endpoint) for r in self._replicas.values()]
        if not targets:
            return

        def one(rid: str, endpoint: str) -> None:
            try:
                payload = probe_fn(endpoint)
            except Exception:  # noqa: BLE001 — unreachable is a signal
                self.probe_failed(rid)
            else:
                self.observe(rid, payload)

        if len(targets) == 1:
            one(*targets[0])
            return
        try:
            futures = [
                self._probe_pool.submit(one, rid, endpoint)
                for rid, endpoint in targets
            ]
        except RuntimeError:  # closed table (fleet deleted mid-sweep)
            return
        for f in futures:
            f.result()

    # -- external transitions ---------------------------------------------

    def mark_draining(self, rid: str) -> None:
        self._mark(rid, DRAINING)

    def mark_cordoned(self, rid: str) -> None:
        self._mark(rid, CORDONED)

    def mark_dead(self, rid: str) -> None:
        self._mark(rid, DEAD)

    def uncordon(self, rid: str) -> None:
        """Back to JOINING (not READY): the next successful probe
        re-promotes it, so an uncordon can never route to a replica that
        died while withdrawn."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None and rep.state == CORDONED:
                self._transition_locked(rep, JOINING)
            self._export_locked()

    def _mark(self, rid: str, state: str) -> None:
        # DEAD is sticky: a dead replica gets replaced, never re-marked.
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None and rep.state != DEAD:
                self._transition_locked(rep, state)
            self._export_locked()

    def _transition_locked(self, rep: Replica, state: str) -> None:
        if rep.state != state:
            LOG.info(f"replica {rep.id}: {rep.state} -> {state}")
            rep.state = state

    # -- router bookkeeping ------------------------------------------------

    def begin(self, rid: str) -> None:
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None:
                rep.inflight += 1

    def end(self, rid: str) -> None:
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None and rep.inflight > 0:
                rep.inflight -= 1

    # -- views -------------------------------------------------------------

    def get(self, rid: str) -> Replica | None:
        with self._lock:
            return self._replicas.get(rid)

    def routable(self) -> list[Replica]:
        with self._lock:
            return [r for r in self._replicas.values() if r.routable]

    def all(self) -> list[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def counts(self) -> dict[str, int]:
        with self._lock:
            out = {s: 0 for s in STATES}
            for rep in self._replicas.values():
                out[rep.state] += 1
            return out

    def note_unrouted(self) -> None:
        """The router failed to place a request (no routable replica)."""
        with self._lock:
            self._unrouted += 1

    def take_unrouted(self) -> int:
        """Unplaced-request count since the last read (drain-on-read;
        the controller feeds it to the autoscaler once per sync)."""
        with self._lock:
            n, self._unrouted = self._unrouted, 0
            return n

    def aggregate_queue_depth(self) -> int:
        with self._lock:
            return sum(
                r.queue_depth for r in self._replicas.values() if r.routable
            )

    def fleet_ttft_p99(self) -> float | None:
        """Worst routable replica's TTFT p99 (None when no probe has
        carried one) — one slow replica is enough for the autoscaler's
        latency trigger."""
        with self._lock:
            vals = [
                r.ttft_p99_s for r in self._replicas.values()
                if r.routable and r.ttft_p99_s is not None
            ]
            return max(vals) if vals else None

    def fleet_itl_p99(self) -> float | None:
        """Worst routable replica's inter-token-latency p99 — the
        decode pool's disaggregation-era latency trigger (one replica
        with interfering prefills or an overloaded step is enough)."""
        with self._lock:
            vals = [
                r.itl_p99_s for r in self._replicas.values()
                if r.routable and r.itl_p99_s is not None
            ]
            return max(vals) if vals else None

    def prefix_directory(self) -> dict[str, int]:
        """Fleet-wide advertisement roll-up for /debug/fleet and
        ``tpuctl serve``: distinct advertised digests and per-replica
        advertisement sizes are summarized as {"digests": distinct,
        "replicas_advertising": n} — counts, not the digests themselves
        (opaque hex noise outside the router)."""
        with self._lock:
            digests: set[str] = set()
            tier_digests: set[str] = set()
            advertising = 0
            tier_advertising = 0
            for r in self._replicas.values():
                if r.prefixes:
                    advertising += 1
                    digests.update(r.prefixes)
                if r.tier_prefixes:
                    tier_advertising += 1
                    tier_digests.update(r.tier_prefixes)
            return {
                "digests": len(digests),
                "replicas_advertising": advertising,
                # Warm host-tier rollup (serve/tier.py): distinct
                # restorable digests across the fleet + how many
                # replicas hold a tier.
                "tier_digests": len(tier_digests),
                "replicas_tier_advertising": tier_advertising,
            }

    def mean_occupancy(self) -> float | None:
        """Mean active-slot fraction across routable replicas (None
        with nothing routable) — the decode pool's capacity scale
        signal: occupancy saturating means decode throughput has, too,
        regardless of what queues look like."""
        with self._lock:
            vals = [
                r.occupancy for r in self._replicas.values() if r.routable
            ]
            return sum(vals) / len(vals) if vals else None

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "replicas": [
                    r.snapshot()
                    for r in sorted(self._replicas.values(),
                                    key=lambda r: r.id)
                ],
                "counts": {
                    s: sum(1 for r in self._replicas.values()
                           if r.state == s)
                    for s in STATES
                },
            }

    def _export_locked(self) -> None:
        for s in STATES:
            FLEET_REPLICAS.set(
                sum(1 for r in self._replicas.values() if r.state == s),
                fleet=self.name, state=s,
            )
        FLEET_QUEUE_DEPTH.set(
            sum(r.queue_depth for r in self._replicas.values()
                if r.routable),
            fleet=self.name,
        )
