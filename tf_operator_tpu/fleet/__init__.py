"""TPUServe fleet serving: long-running serve replicas behind an
occupancy-aware router with queue-depth autoscaling.

The composition layer over everything the operator already has: the
gang scheduler admits each replica (PR 1), fleet health cordons sick
cells under them (PR 2), and the supervised continuous engine makes a
single replica safe to route to (PRs 5–7). This package adds the fleet
abstractions — membership (which replicas are routable), the router
(where one request goes, and where it retries), the autoscaler (how
many replicas there should be), and the TPUServe controller (making it
so). See docs/fleet-serving.md.
"""

from tf_operator_tpu.fleet.autoscale import Autoscaler, AutoscaleSnapshot
from tf_operator_tpu.fleet.controller import FleetConfig, TPUServeController
from tf_operator_tpu.fleet.membership import FleetMembership, Replica
from tf_operator_tpu.fleet.prefixes import (
    AffinityTable,
    PrefixConfig,
    best_replica,
    hit_blocks,
    holder_of,
    prefix_score,
    request_digests,
)
from tf_operator_tpu.fleet.replica import (
    FakeReplicaBackend,
    ReplicaServer,
    SupervisorBackend,
    fleet_of,
)
from tf_operator_tpu.fleet.router import (
    DisaggConfig,
    DisaggRouter,
    DisaggRouterServer,
    FleetRouter,
    RouterConfig,
    RouterServer,
)

__all__ = [
    "AffinityTable",
    "Autoscaler",
    "AutoscaleSnapshot",
    "DisaggConfig",
    "DisaggRouter",
    "DisaggRouterServer",
    "FakeReplicaBackend",
    "FleetConfig",
    "FleetMembership",
    "FleetRouter",
    "PrefixConfig",
    "Replica",
    "ReplicaServer",
    "RouterConfig",
    "RouterServer",
    "SupervisorBackend",
    "TPUServeController",
    "best_replica",
    "fleet_of",
    "hit_blocks",
    "holder_of",
    "prefix_score",
    "request_digests",
]
