"""Queue-depth / TTFT autoscaling for a TPUServe fleet.

Pure decision logic: ``Autoscaler.decide`` maps one fleet observation
(ready replicas, aggregate queue depth, TTFT p99) to a target replica
count. The controller applies the target by creating/draining child
jobs; the policy never touches the cluster.

Policy (api/serve_types.AutoscalePolicy):

- SCALE UP by one when queued requests per READY replica exceed
  ``queue_high`` — backlog is the direct "users are waiting" signal the
  replicas already export (tpu_serve_queue_depth) — or when fleet TTFT
  p99 exceeds ``ttft_p99_high_s`` (queues can look short while every
  slot is pinned by long generations; latency catches that).
- SCALE DOWN by one when backlog per replica drops under ``queue_low``
  and the latency trigger is quiet. The ``queue_low < queue_high``
  hysteresis band plus per-direction cooldowns prevent flapping; the
  asymmetric defaults (up fast, down slow) are deliberate — a missing
  replica costs user latency, a spare one only costs chips.
- One step per decision: admission of a new replica takes seconds
  (checkpoint load + warmup), so reacting to the same backlog twice
  before the first new replica is READY would overshoot. Draining
  replicas do not count as capacity (they take no new work) but also do
  not block scale-up.

Targets clamp to [min_replicas, max_replicas] always — even manual
``spec.replicas`` edits pass through the same clamp in the controller.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

from tf_operator_tpu.api.serve_types import AutoscalePolicy
from tf_operator_tpu.runtime.metrics import FLEET_AUTOSCALE_TOTAL
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="fleet-autoscale")

__all__ = ["AutoscalePolicy", "AutoscaleSnapshot", "Autoscaler"]


@dataclass
class AutoscaleSnapshot:
    """One observation of the fleet, as the controller's probe sweep
    sees it."""

    ready: int
    queue_depth: int              # aggregate across routable replicas
    ttft_p99_s: float | None = None
    # Requests the router answered no_replica since the last sync —
    # the only demand signal a fleet scaled to zero can emit (nothing
    # exists to queue on, so queue_depth is structurally 0).
    unrouted: int = 0
    # Decode-pool signals (disaggregated serving): mean active-slot
    # fraction and worst inter-token-latency p99 across routable
    # replicas. A decode pool saturates its SLOTS and its STEP TIME
    # before its queues move (shipped joins cost almost nothing to
    # admit), so queue depth alone under-scales it — these two are what
    # the policy's occupancy_high / itl_p99_high_s thresholds read.
    occupancy: float | None = None
    itl_p99_s: float | None = None


class Autoscaler:
    def __init__(self, policy: AutoscalePolicy) -> None:
        self.policy = policy
        self.last_scale_up_at: float | None = None
        self.last_scale_down_at: float | None = None
        self.last_reason = ""

    def clamp(self, target: int) -> int:
        return max(self.policy.min_replicas,
                   min(self.policy.max_replicas, target))

    def decide(self, snap: AutoscaleSnapshot, current_target: int,
               now: float | None = None) -> int:
        """New target replica count given the observation; returns
        ``current_target`` (clamped) when nothing should change."""
        pol = self.policy
        if not pol.enabled:
            return current_target
        now = time.monotonic() if now is None else now
        target = self.clamp(current_target)
        # No READY capacity at all with work queued is an immediate
        # scale-up signal regardless of the per-replica ratio.
        per_replica = (
            snap.queue_depth / snap.ready if snap.ready
            else float(snap.queue_depth)
        )
        ttft_high = bool(
            pol.ttft_p99_high_s
            and snap.ttft_p99_s is not None
            and snap.ttft_p99_s > pol.ttft_p99_high_s
        )
        occ_high = bool(
            pol.occupancy_high
            and snap.occupancy is not None
            and snap.occupancy > pol.occupancy_high
        )
        itl_high = bool(
            pol.itl_p99_high_s
            and snap.itl_p99_s is not None
            and snap.itl_p99_s > pol.itl_p99_high_s
        )
        latency_high = ttft_high or occ_high or itl_high
        # A fleet at target 0 has no queues and no TTFT — rejected
        # (no_replica) requests are its scale-up signal, and ANY demand
        # against zero capacity warrants the first replica; without this
        # a minReplicas=0 fleet that drained to zero could never come
        # back.
        cold_start = current_target == 0 and snap.unrouted > 0
        want_up = (per_replica > pol.queue_high or latency_high
                   or cold_start)
        want_down = (
            not want_up
            and not latency_high
            and per_replica < pol.queue_low
        )
        if not want_down:
            # Load is present: a later lull must wait a full cooldown
            # again before the first down-step.
            self.last_scale_down_at = None
        if want_up and target < pol.max_replicas:
            if (self.last_scale_up_at is not None
                    and now - self.last_scale_up_at
                    < pol.scale_up_cooldown_s):
                return target
            self.last_scale_up_at = now
            if ttft_high and snap.ttft_p99_s is not None:
                self.last_reason = (
                    f"ttft_p99 {snap.ttft_p99_s:.3f}s > "
                    f"{pol.ttft_p99_high_s}s"
                )
            elif itl_high and snap.itl_p99_s is not None:
                self.last_reason = (
                    f"itl_p99 {snap.itl_p99_s:.3f}s > "
                    f"{pol.itl_p99_high_s}s"
                )
            elif occ_high and snap.occupancy is not None:
                self.last_reason = (
                    f"occupancy {snap.occupancy:.2f} > "
                    f"{pol.occupancy_high}"
                )
            elif per_replica > pol.queue_high:
                self.last_reason = (
                    f"queue/replica {per_replica:.1f} > {pol.queue_high}"
                )
            else:
                self.last_reason = (
                    f"{snap.unrouted} unrouted request(s) against "
                    "zero capacity"
                )
            FLEET_AUTOSCALE_TOTAL.inc(direction="up")
            LOG.info(
                f"scale up {target} -> {target + 1}: {self.last_reason}"
            )
            return target + 1
        if want_down and target > pol.min_replicas:
            if (self.last_scale_down_at is not None
                    and now - self.last_scale_down_at
                    < pol.scale_down_cooldown_s):
                return target
            # The down cooldown also starts at the first eligible
            # observation rather than firing on it: one idle probe after
            # a burst must not shrink the fleet.
            if self.last_scale_down_at is None:
                self.last_scale_down_at = now
                return target
            self.last_scale_down_at = now
            self.last_reason = (
                f"queue/replica {per_replica:.1f} < {pol.queue_low}"
            )
            FLEET_AUTOSCALE_TOTAL.inc(direction="down")
            LOG.info(
                f"scale down {target} -> {target - 1}: {self.last_reason}"
            )
            return target - 1
        return target

    def snapshot(self) -> dict[str, Any]:
        return {
            "enabled": self.policy.enabled,
            "min": self.policy.min_replicas,
            "max": self.policy.max_replicas,
            "queue_high": self.policy.queue_high,
            "queue_low": self.policy.queue_low,
            "ttft_p99_high_s": self.policy.ttft_p99_high_s,
            "itl_p99_high_s": self.policy.itl_p99_high_s,
            "occupancy_high": self.policy.occupancy_high,
            "last_reason": self.last_reason,
        }
