"""Fleet-global prefix reuse: the routing-side state and policy.

PR 6 gave each replica a PrefixCache; PR 9 gave the fleet a router that
scores load alone. The result at fleet scale is the worst of both: the
same 8k-token system prompt is re-prefilled once per replica, because
the replica that already holds it looks exactly as attractive as the
one that doesn't. This module closes that gap with three pieces of
fleet state, all riding surfaces that already exist:

- **Advertisement** — every replica's /healthz readiness payload grows
  a ``prefixes`` list: the hex chained per-block SHA-1 digests of its
  hottest PrefixCache entries (MRU first, capped engine-side; the
  digest chain is the SAME one the PR 14 shipped-KV wire format
  carries, so router and replica hash identically by construction).
  ``FleetMembership.observe`` ingests it on every probe sweep with the
  clear-on-absent contract the latency signals use.

- **Scoring** — the router chains the request's own digests
  (``disagg.chain_digests``, jax-free) and picks by
  ``load - weight * hit_fraction`` instead of load alone:
  ``hit_fraction`` is the longest advertised prefix of the request's
  chain over its total blocks, so a full-prompt hit on an
  equally-loaded replica always wins the tiebreak, and ``weight``
  prices how much queued work a prefix hit is allowed to buy
  (weight=0 degrades to exactly the PR 9 least-loaded pick).

- **Affinity** — multi-turn traffic carries a ``session`` key; the
  router remembers each session's home replica (LRU-capped table) and
  routes it home while home stays routable, so every turn after the
  first lands on the replica that holds the conversation's blocks. A
  DRAINING/CORDONED/DEAD home falls out of ``routable()`` and the
  session re-homes through the scored pick — rolling updates re-home,
  they never 5xx.

On a prefix miss at the chosen replica the router can *pull*: if
another routable replica advertises the request's exact whole-prompt
digest, ``GET /prefix/<digest>`` exports that entry in the PR 14 wire
format and the payload rides the dispatch as ``shipped_kv``, landing
through the ordinary ``ingest_shipment`` → exact-prefix table-insert
join — bit-identical to decoding on the holder. Every failure in that
chain (the typed ``prefix_not_found`` stale-advertisement race, a
transport error, a ``ship_failed`` rejection at the decode side)
degrades to local prefill; the pull is an optimization, never a new
way to fail a request.

Deliberately jax-free, like the rest of fleet/: the router tier tests
run without an accelerator stack.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Iterable, Sequence

from tf_operator_tpu.serve.disagg import chain_digests

__all__ = [
    "AffinityTable",
    "PrefixConfig",
    "best_replica",
    "hit_blocks",
    "holder_of",
    "prefix_score",
    "request_digests",
]


@dataclass
class PrefixConfig:
    """Router-side knobs for prefix-aware routing (the TPUServe spec's
    ``prefixRouting`` block renders into one of these).

    ``kv_block`` MUST match the replica engines' paged block size: the
    digest chain is block-aligned, and a router chaining with the wrong
    block computes digests no replica ever advertises — prefix routing
    silently degrades to least-loaded (safe, but pointless).
    """

    kv_block: int = 64
    # Load units a FULL prefix hit is worth: score = load - weight *
    # hit_fraction. Replica load is (active + queued + inflight) /
    # max_slots, so weight=1.0 lets a full hit outbid one max_slots'
    # worth of queued work; weight=0.0 is exactly least-loaded.
    weight: float = 1.0
    session_affinity: bool = True
    pull: bool = True
    pull_timeout_s: float = 5.0
    affinity_capacity: int = 4096
    # Plumbed to replica engines (prefix_advertise_max), echoed here so
    # the spec carries one coherent block; the router never reads it.
    advertise_max: int = 32
    # KV memory hierarchy (serve/tier.py): what fraction of a hot hit a
    # WARM host-tier hit is worth in the score. A tier hit saves the
    # prefill compute but still pays the host→HBM upload at admission,
    # so it outbids a cold replica and loses to an equally-loaded hot
    # one. 0.0 ignores tier advertisements entirely.
    tier_discount: float = 0.5

    @classmethod
    def from_policy(cls, policy: Any) -> "PrefixConfig | None":
        """Render a TPUServe spec ``prefixRouting`` block
        (api/serve_types.PrefixRoutingPolicy, duck-typed so the api
        layer stays import-free of fleet/) into the router's config.
        None when the block is absent or disabled — the router then
        keeps the plain least-loaded pick."""
        if policy is None or not getattr(policy, "enabled", False):
            return None
        return cls(
            kv_block=int(policy.kv_block),
            weight=float(policy.weight),
            session_affinity=bool(policy.session_affinity),
            pull=bool(policy.pull),
            pull_timeout_s=float(policy.pull_timeout_s),
            advertise_max=int(policy.advertise_max),
            # getattr: specs predating the KV tier carry no knob — keep
            # the default discount rather than failing the render.
            tier_discount=float(getattr(policy, "tier_discount", 0.5)),
        )


def request_digests(tokens: Any, kv_block: int) -> tuple[str, ...]:
    """The request prompt's chained per-block digest chain (hex,
    shortest first) — ``disagg.chain_digests`` under a fleet-side name;
    the last element is the exact whole-prompt digest a pull targets."""
    return tuple(chain_digests(tokens, kv_block))


def hit_blocks(digests: Sequence[str], advertised: Iterable[str]) -> int:
    """Chain positions of ``digests`` covered by an advertisement: the
    LONGEST k with digests[k-1] advertised. The chain construction makes
    position k imply the replica holds blocks [0, k) of this prompt —
    later positions chain over earlier bytes — so the deepest advertised
    digest, not the count of matches, is the reuse measure (the
    advertisement is capped and need not list every ancestor)."""
    adv = advertised if isinstance(advertised, (set, frozenset)) \
        else frozenset(advertised)
    hit = 0
    for k, d in enumerate(digests):
        if d in adv:
            hit = k + 1
    return hit


def prefix_score(load: float, hit: int, total: int,
                 weight: float, tier_hit: int = 0,
                 tier_discount: float = 0.0) -> float:
    """``load - weight * effective_hit_fraction`` — lower wins.
    ``effective`` counts hot blocks at full value and the WARM
    host-tier blocks BEYOND the hot hit at ``tier_discount`` (a tier
    hit skips the prefill compute but still pays the restore upload):
    ``hit/total + discount * max(0, tier_hit - hit)/total``. The
    defaults (tier_hit=0, discount=0) reproduce the pre-tier score
    exactly. Documented in docs/fleet-serving.md and
    docs/kv-tiering.md; keep the three in sync."""
    if not total:
        return load
    frac = hit / total
    if tier_hit > hit and tier_discount:
        frac += tier_discount * (tier_hit - hit) / total
    return load - weight * frac


def best_replica(replicas: Sequence[Any], digests: Sequence[str],
                 weight: float, tier_discount: float = 0.0):
    """The prefix-hit-weighted-by-load pick: min score, ties broken by
    (load, id) so equal-score candidates keep the PR 9 deterministic
    order and an equal-LOAD candidate with a deeper prefix hit wins
    (its score is strictly lower). With ``tier_discount`` > 0 a
    replica's WARM host-tier advertisement counts as a discounted hit
    (serve/tier.py) — restorable beats recompute, hot beats
    restorable. Returns ``(replica, hit_blocks)`` with the HOT hit
    depth (the pull gate keys off what is live); (None, 0) on no
    candidates."""
    best = None
    best_hit = 0
    best_key = None
    for r in replicas:
        hit = hit_blocks(digests, getattr(r, "prefixes", ()) or ())
        tier_hit = hit_blocks(
            digests, getattr(r, "tier_prefixes", ()) or ()
        ) if tier_discount else 0
        key = (prefix_score(r.load, hit, len(digests), weight,
                            tier_hit, tier_discount),
               r.load, r.id)
        if best_key is None or key < best_key:
            best, best_hit, best_key = r, hit, key
    return best, best_hit


def holder_of(replicas: Sequence[Any], digest: str,
              exclude: Iterable[str] = ()):
    """The least-loaded routable replica advertising ``digest`` (the
    pull source), excluding ids in ``exclude`` (the chosen replica —
    pulling from yourself is a no-op — and anything the retry loop
    already struck out). A WARM host-tier advertisement counts too —
    the holder's /prefix/<digest> export answers from its tier when
    the entry is no longer hot (serve/tier.py), same wire format — but
    hot holders are preferred at equal exclusion (their export needs
    no tier lookup and proves the entry live). None when nobody
    advertises it at either level."""
    skip = set(exclude)
    holders = [
        r for r in replicas
        if r.id not in skip
        and (digest in (getattr(r, "prefixes", ()) or ())
             or digest in (getattr(r, "tier_prefixes", ()) or ()))
    ]
    if not holders:
        return None
    return min(holders, key=lambda r: (
        digest not in (getattr(r, "prefixes", ()) or ()), r.load, r.id
    ))


class AffinityTable:
    """session -> home replica id, LRU-capped and thread-safe (router
    handler threads write on every successful route; the probe thread
    never touches it). The table stores ROUTING PREFERENCE, not truth:
    a home that stopped being routable is simply ignored by the caller
    and overwritten on the next successful route, so there is no
    invalidation protocol to get wrong — a rolling update re-homes
    every session it touches and nothing 5xxs on stale entries."""

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = max(1, int(capacity))
        self._homes: dict[str, str] = {}
        self._lock = threading.Lock()

    def home(self, session: str) -> str | None:
        """The session's home replica id (recency-refreshing), or None
        for a first-turn/unknown session."""
        if not session:
            return None
        with self._lock:
            rid = self._homes.get(session)
            if rid is not None:
                self._homes[session] = self._homes.pop(session)
            return rid

    def set_home(self, session: str, rid: str) -> None:
        """Record where the session's turn actually served (called on
        SUCCESS only — a failed dispatch must not re-home the session
        onto the replica that just failed it)."""
        if not session or not rid:
            return
        with self._lock:
            self._homes.pop(session, None)
            self._homes[session] = rid
            while len(self._homes) > self.capacity:
                self._homes.pop(next(iter(self._homes)))

    def forget_replica(self, rid: str) -> None:
        """Drop every session homed on ``rid`` — optional hygiene when
        membership marks a replica DEAD (stale homes are harmless, this
        just keeps the table from pinning them until LRU eviction)."""
        with self._lock:
            for s in [s for s, r in self._homes.items() if r == rid]:
                self._homes.pop(s, None)

    @property
    def sessions(self) -> int:
        with self._lock:
            return len(self._homes)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "sessions": len(self._homes),
                "capacity": self.capacity,
            }
