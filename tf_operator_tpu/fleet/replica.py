"""In-process serve replica: serve_lm's HTTP surface over an injected
backend, for fleets that live inside one process.

serve_lm is the production replica — one process, one engine, one port.
Fleet tests and the fleet bench leg need FOUR of those at once on a CPU
host, where four serve_lm subprocesses would mean four jax inits and
four quick-trained models. ``ReplicaServer`` keeps the contract and
drops the processes: the same three endpoints (``/healthz`` via
serve/httpapi.readiness_payload — the exact probe shape
fleet/membership.py routes from — plus ``/generate`` with PR 7's typed
error payloads and ``/metrics``), backed by either

- ``SupervisorBackend``: a real supervised continuous engine
  (serve/resilience.EngineSupervisor) — the bench's replica, or
- ``FakeReplicaBackend``: jax-free and scriptable (canned tokens,
  service delay, injected typed errors, settable load numbers) — the
  fast routing/retry/autoscale test tier.

Because several replicas share one process, the server stamps its
``replica`` id onto every response explicitly rather than through
serve/resilience's process-global ``set_replica_id`` channel (which is
serve_lm's one-replica-per-process shortcut).

Lifecycle hooks mirror what the fleet controller does to real replicas:
``begin_drain()`` flips readiness (healthz ``draining: true``, new
/generate refused with the typed ``draining`` error) while in-flight
requests finish; ``kill()`` drops the socket dead — the transport
failure the router's failover path exists for.
"""

from __future__ import annotations

import threading
import time
from http.server import ThreadingHTTPServer
from typing import Any

from tf_operator_tpu.runtime.tracing import SERVE_TRACER, mint_request_id
from tf_operator_tpu.serve.httpapi import QuietHandler, readiness_payload
from tf_operator_tpu.serve.resilience import (
    Draining,
    PrefixNotFound,
    TierMiss,
    error_payload,
    http_status_of,
)
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="fleet-replica")


class SupervisorBackend:
    """A real supervised continuous engine behind the replica surface.

    ``handle`` maps one /generate body through
    ``EngineSupervisor.submit_request`` with serve_lm's response shape:
    200 + generated tokens (``deadline_exceeded``/``timeout_cause``
    flags when the deadline or drain cut rows short), typed
    ServeError -> its ``http_status`` + payload.
    """

    role = "decode"

    def __init__(self, supervisor: Any, *,
                 request_timeout_s: float = 120.0) -> None:
        self.supervisor = supervisor
        self.request_timeout_s = request_timeout_s

    # Load picture proxied for readiness_payload. max_slots included:
    # without it the probe payload omits capacity and membership
    # normalizes this replica's load by 1 — raw backlog instead of
    # occupancy, which skews the least-loaded pick on mixed-capacity
    # fleets.
    @property
    def max_slots(self) -> int:
        return self.supervisor.max_slots

    @property
    def active_slots(self) -> int:
        return self.supervisor.active_slots

    @property
    def queue_depth(self) -> int:
        return self.supervisor.queue_depth

    @property
    def requests_done(self) -> int:
        return self.supervisor.requests_done

    @property
    def tokens_generated(self) -> int:
        return self.supervisor.tokens_generated

    @property
    def restarts(self) -> int:
        return self.supervisor.restarts

    @property
    def dead(self) -> bool:
        return self.supervisor.dead

    def debug_snapshot(self) -> dict[str, Any]:
        return self.supervisor.debug_snapshot()

    def advertised_prefixes(self) -> list[str]:
        """The engine's hot-prefix advertisement — readiness_payload
        duck-types this off the backend, so real replicas advertise
        through the same /healthz shape the fakes script."""
        return self.supervisor.advertised_prefixes()

    def advertised_tier_prefixes(self) -> list[str]:
        """The warm host-tier advertisement (serve/tier.py) — rides the
        same /healthz probe as the hot list, as ``tier_prefixes``."""
        return self.supervisor.advertised_tier_prefixes()

    def export_prefix(self, digest: str) -> dict[str, Any]:
        """GET /prefix/<digest>: the supervised engine's wire-format
        export (raises the typed PrefixNotFound on stale digests)."""
        return self.supervisor.export_prefix(digest)

    def handle(self, body: dict[str, Any]) -> tuple[int, dict[str, Any]]:
        import numpy as np

        from tf_operator_tpu.serve.scheduler import ServeRequest

        try:
            tokens = np.asarray(body["tokens"], np.int32)
            if tokens.ndim != 2:
                raise ValueError("tokens must be [batch, len]")
        except (KeyError, ValueError, TypeError) as exc:
            return 400, {"error": str(exc), "code": "bad_request",
                         "retryable": False, "detail": str(exc)}
        shipment = None
        if body.get("shipped_kv") is not None:
            # Disaggregated prefill: verify the payload BEFORE it
            # reaches the scheduler — a digest/token mismatch answers
            # typed ship_failed (the disagg router re-prefills; it
            # never retries the same bytes on another decode replica).
            from tf_operator_tpu.serve.disagg import decode_shipment
            from tf_operator_tpu.serve.resilience import ShipFailed

            try:
                shipment = decode_shipment(
                    body["shipped_kv"], expect_tokens=tokens[0]
                )
            except ShipFailed as exc:
                return http_status_of(exc), error_payload(exc)
        try:
            req = ServeRequest(
                tokens[:1], int(body.get("num_steps", 8)),
                temperature=float(body.get("temperature", 0.0)),
                top_p=body.get("top_p"),
                seed=int(body.get("seed", 0)),
                deadline_s=body.get("deadline_s"),
                # The fleet hop: the router-minted (or client-supplied)
                # id becomes the scheduler/engine span key, so the
                # merged trace follows one request across processes.
                request_id=body.get("request_id"),
                shipment=shipment,
                # The same session key the router uses for affinity also
                # pre-warms the host KV tier (serve/tier.py): enqueue
                # kicks an async restore so the blocks are hot by
                # admission.
                session=body.get("session"),
                # Structured decoding rides the fleet hop verbatim: the
                # grammar spec compiles on the REPLICA's scheduler (its
                # compiler owns the vocab closure); an invalid grammar
                # answers the typed 400 through submit_request below.
                constrain=({
                    k: body[k]
                    for k in ("json_schema", "regex", "choices")
                    if body.get(k) is not None
                } or None),
                stop=body.get("stop"),
                logprobs=bool(body.get("logprobs")),
            )
        except (KeyError, ValueError, TypeError) as exc:
            return 400, {"error": str(exc), "code": "bad_request",
                         "retryable": False, "detail": str(exc)}
        try:
            req = self.supervisor.submit_request(
                req, timeout=self.request_timeout_s
            )
        except Exception as exc:  # noqa: BLE001 — every failure leaves
            # typed (ServeError renders itself; the rest become 500s).
            return http_status_of(exc), error_payload(exc)
        payload: dict[str, Any] = {"tokens": [list(req.out)]}
        if req.finish_reason:
            payload["finish_reason"] = [req.finish_reason]
        if req.logprobs and req.logprob_rows:
            payload["logprobs"] = [req.logprob_rows]
        if req.deadline_exceeded:
            payload["deadline_exceeded"] = [True]
            payload["timeout_cause"] = [req.timeout_cause]
        if req.degraded:
            payload["degraded"] = [True]
        if body.get("timing"):
            # Compact per-request latency attribution (queue/prefill/
            # decode ms + ITL summary) — opt-in, one list entry per row
            # to match the tokens shape.
            payload["timing"] = [req.timing()]
        return 200, payload


class FakeReplicaBackend:
    """A jax-free replica brain for the fast fleet test tier.

    Serves canned generations (``num_steps`` zeros) after
    ``service_delay_s``; everything the routing/retry/autoscale layers
    read is directly settable (``queue_depth``, ``ttft_p99_s``,
    ``dead``), and ``fail_with(exc, n)`` scripts the next n /generate
    calls to resolve as that typed error — so a test drives the exact
    taxonomy the router keys on without an engine in sight.
    """

    role = "decode"

    def __init__(self, *, max_slots: int = 8,
                 service_delay_s: float = 0.0) -> None:
        self.max_slots = max_slots
        self.service_delay_s = service_delay_s
        self.queue_depth = 0
        self.requests_done = 0
        self.tokens_generated = 0
        self.restarts = 0
        self.dead = False
        self.ttft_p99_s: float | None = None
        self.itl_p99_s: float | None = None
        # Shipped-KV bodies seen (disagg chaos tier asserts the routed
        # payload actually reached a decode replica).
        self.shipped_received = 0
        # Fleet-global prefix reuse, scriptable: ``prefixes`` is what
        # /healthz advertises; ``prefix_store`` maps digest -> the wire
        # payload GET /prefix/<digest> serves (absent digest answers
        # the typed prefix_not_found — the stale-advertisement script:
        # advertise a digest WITHOUT storing it).
        self.prefixes: list[str] = []
        self.prefix_store: dict[str, dict] = {}
        self.prefix_exports = 0
        # KV memory hierarchy (serve/tier.py), scriptable the same way:
        # ``tier_prefixes`` is the /healthz warm advertisement;
        # ``tier_store`` backs GET /prefix/<digest> as a SECOND lookup
        # level behind ``prefix_store`` — exactly how a real replica's
        # export falls back to its host tier. A digest advertised in
        # neither store scripts the typed tier_miss.
        self.tier_prefixes: list[str] = []
        self.tier_store: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._inflight = 0
        self._scripted: list[Exception] = []

    @property
    def active_slots(self) -> int:
        with self._lock:
            return min(self._inflight, self.max_slots)

    def fail_with(self, exc: Exception, n: int = 1) -> None:
        with self._lock:
            self._scripted.extend(exc for _ in range(n))

    def advertised_prefixes(self) -> list[str]:
        return list(self.prefixes)

    def advertised_tier_prefixes(self) -> list[str]:
        return list(self.tier_prefixes)

    def export_prefix(self, digest: str) -> dict[str, Any]:
        payload = self.prefix_store.get(digest)
        if payload is None:
            # Warm-tier fallback, mirroring the real engine's export:
            # a spilled entry still answers the pull from host RAM.
            payload = self.tier_store.get(digest)
        if payload is None:
            if digest in self.tier_prefixes:
                # Advertised warm but gone from the tier (byte-budget
                # eviction raced the pull): the typed tier_miss — the
                # puller degrades to local prefill, like any 404 here.
                raise TierMiss(
                    f"tier entry {digest[:12]} evicted before pull"
                )
            raise PrefixNotFound(f"no live exact prefix entry for "
                                 f"{digest[:12]}")
        with self._lock:
            self.prefix_exports += 1
        return payload

    def handle(self, body: dict[str, Any]) -> tuple[int, dict[str, Any]]:
        with self._lock:
            self._inflight += 1
            if body.get("shipped_kv") is not None:
                self.shipped_received += 1
            scripted = self._scripted.pop(0) if self._scripted else None
        try:
            if scripted is not None:
                return http_status_of(scripted), error_payload(scripted)
            if self.service_delay_s:
                import time

                time.sleep(self.service_delay_s)
            steps = int(body.get("num_steps", 8))
            rows = body.get("tokens") or [[0]]
            out = [[0] * steps for _ in rows[:1]]
            with self._lock:
                self.requests_done += 1
                self.tokens_generated += steps
            return 200, {"tokens": out}
        finally:
            with self._lock:
                self._inflight -= 1


class ReplicaServer:
    """One replica endpoint: /healthz + /generate + /metrics over a
    backend, with the fleet lifecycle hooks (drain, kill)."""

    def __init__(self, backend: Any, *, replica_id: str,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.backend = backend
        self.replica_id = replica_id
        self._draining = False
        outer = self

        class Handler(QuietHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    payload = readiness_payload(
                        outer.backend, draining=outer._draining,
                        replica=outer.replica_id,
                        max_slots=getattr(outer.backend, "max_slots",
                                          None),
                        role=getattr(outer.backend, "role", ""),
                    )
                    # Scriptable latency for the autoscaler tier: a
                    # FakeReplicaBackend pins its own p99s instead of
                    # the process-global histograms shared by every
                    # in-process replica.
                    ttft = getattr(outer.backend, "ttft_p99_s", None)
                    if ttft is not None:
                        payload["ttft_p99_s"] = float(ttft)
                    itl = getattr(outer.backend, "itl_p99_s", None)
                    if itl is not None:
                        payload["itl_p99_s"] = float(itl)
                    self.send_json(200, payload)
                elif path == "/debug/serve" and hasattr(
                    outer.backend, "debug_snapshot"
                ):
                    self.send_json(200, outer.backend.debug_snapshot())
                elif path.startswith("/prefix/") and hasattr(
                    outer.backend, "export_prefix"
                ):
                    # Fleet-global prefix reuse: export one live
                    # PrefixCache entry in the shipped-KV wire format.
                    # Stale digests answer the typed prefix_not_found
                    # (404) — the pulling router degrades to local
                    # prefill, never fails the request.
                    digest = path[len("/prefix/"):]
                    try:
                        shipment = outer.backend.export_prefix(digest)
                    except Exception as exc:  # noqa: BLE001 — typed out
                        payload = error_payload(exc)
                        payload["replica"] = outer.replica_id
                        self.send_json(http_status_of(exc), payload)
                        return
                    self.send_json(200, {
                        "shipment": shipment,
                        "replica": outer.replica_id,
                    })
                elif path == "/debug/traces":
                    self.send_serve_traces()
                elif path == "/metrics":
                    self.send_metrics()
                else:
                    self.send_json(404, {"error": "unknown path"})

            def do_POST(self):
                if self.path.split("?", 1)[0] != "/generate":
                    self.send_json(404, {"error": "unknown path"})
                    return
                try:
                    body = self.read_json_body()
                except ValueError:
                    self.send_json(400, {"error": "bad JSON",
                                         "code": "bad_request",
                                         "retryable": False,
                                         "replica": outer.replica_id})
                    return
                # Accept the upstream id (router-minted, or the
                # client's own via body/header) or mint here: the
                # replica HTTP hop is traced either way.
                rid = (body.get("request_id")
                       or self.headers.get("X-Request-Id")
                       or mint_request_id())
                body["request_id"] = rid
                if outer._draining:
                    exc = Draining("replica draining (scale-down or "
                                   "rolling update)")
                    payload = error_payload(exc)
                    payload["replica"] = outer.replica_id
                    payload["request_id"] = rid
                    self.send_json(exc.http_status, payload)
                    return
                t0 = time.monotonic()
                status, payload = outer.backend.handle(body)
                # The replica-side hop span: even a jax-free fake
                # backend appears in the fleet trace (the propagation
                # tests key on this).
                SERVE_TRACER.record(
                    "replica.request", t0, time.monotonic(),
                    request_id=rid, replica=outer.replica_id,
                    status=status,
                )
                # Attribute every answer, success or typed error —
                # several replicas share this process, so the
                # process-global resilience channel cannot.
                payload = dict(payload)
                payload["replica"] = outer.replica_id
                payload["request_id"] = rid
                self.send_json(status, payload)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread: threading.Thread | None = None

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def draining(self) -> bool:
        return self._draining

    def start(self) -> "ReplicaServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"replica-{self.replica_id}",
        )
        self._thread.start()
        LOG.info(f"replica {self.replica_id} listening on {self.endpoint}")
        return self

    def begin_drain(self) -> None:
        """Readiness withdrawal: /healthz reports ``draining: true`` and
        new /generate calls get the typed ``draining`` refusal while
        in-flight requests finish — the serve_lm SIGTERM shape."""
        self._draining = True

    def kill(self) -> None:
        """Drop dead mid-flight: close the socket without a drain. The
        router sees transport failures and fails over; the membership
        fail threshold declares the replica DEAD."""
        self._server.shutdown()
        self._server.server_close()

    def stop(self) -> None:
        self.kill()


def fleet_of(n: int, backend_factory, *, id_prefix: str = "rep",
             register_in: Any = None) -> list[ReplicaServer]:
    """Spin up n started replicas (backend_factory(i) -> backend); when
    ``register_in`` (a FleetMembership) is given, each is registered
    under its replica id — the two-liner every fleet test starts with."""
    servers = [
        ReplicaServer(backend_factory(i),
                      replica_id=f"{id_prefix}{i}").start()
        for i in range(n)
    ]
    if register_in is not None:
        for s in servers:
            register_in.register(s.replica_id, s.endpoint)
    return servers
