"""The TPUServe controller: N serve replicas, kept alive and routable.

Reconciled alongside TPUJob: this controller turns each TPUServe into
child TPUJobs (one per replica, named ``{serve}-r{index}``), and the
existing TPUJobController does everything below that line — gang
admission, pod creation, gate release, restart policy. What lives HERE
is the fleet layer neither controller has: membership (which replicas
exist and whether they are routable), traffic withdrawal (drain /
cordon → router eviction BEFORE processes die), replacement of dead
replicas, queue-depth/TTFT autoscaling, and rolling model updates.

Reconcile pipeline, per TPUServe, every sync:

1. **Register + probe.** Every child job's replica is registered in the
   per-fleet membership table; one probe sweep ingests each replica's
   /healthz (``ok``/``draining``/``dead`` + occupancy/queue depth —
   serve_lm's PR 9 readiness surface). Probe transport is injected so
   tests and the operator share this code.
2. **Cordon eviction.** Replicas whose child gang sits on cordoned
   cells (health/monitor.py drives the cordon; the scheduler reports
   the overlap) are marked CORDONED — withdrawn from routing while the
   health machinery migrates the gang — and return to routing via
   JOINING once re-placed on healthy cells.
3. **Autoscale.** The per-fleet Autoscaler maps (ready replicas,
   aggregate queue depth, fleet TTFT p99) to a target count, clamped to
   the policy bounds; disabled policies pin target = spec.replicas.
4. **Rolling update.** When ``spec.modelVersion`` changes, old-version
   replicas are replaced one at a time: surge a new-version replica at
   a fresh index, wait until it probes READY, then drain the old one —
   traffic cuts over only when the replacement demonstrably serves, so
   the handoff drops nothing (the drain below guarantees the old
   replica's admitted requests finish).
5. **Scale to target.** Missing replicas are created at fresh indices;
   excess replicas DRAIN rather than die: the membership row flips
   DRAINING (router deregisters it immediately — no drain-window 503s),
   ``fleet.tpuflow.org/draining-at`` is stamped on the child job (the
   scheduler exempts draining gangs from preemption — the drain IS the
   eviction), and only after ``scaleDownGraceSeconds`` is the child
   deleted, handing the process the SIGTERM bounded drain (PR 7's
   ``--drain-timeout``) in which admitted requests complete.
6. **Replace the dead.** A replica whose /healthz says ``dead`` (restart
   budget exhausted) or that stopped answering probes entirely is
   deleted immediately (nothing is draining — it serves nothing) and
   recreated at the lowest free index. Freed indices (and with them
   ports, ``portBase + index``) sit out ``index_quarantine_s`` before
   reuse, so a half-dead predecessor still tearing down can never
   squat its successor's endpoint — but unlike strictly-fresh max+1
   allocation, a long-lived fleet's indices stay bounded by its width
   instead of walking the port out of range one replacement at a time.
7. **Status roll-up.** Replica/ready/draining/dead counts, the current
   target, and a FleetReady condition land on the TPUServe status
   (skip-unchanged, conflict-retried) — the ``tpuctl serve`` surface.

Membership is PER FLEET (replicas of different TPUServes serve
different models and must never share a router pick-set); a router is
built over one fleet's table via ``membership_for``.
"""

from __future__ import annotations

import copy
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.serve_types import (
    ANNOTATION_MODEL_VERSION,
    ENV_SERVE_MODEL_VERSION,
    ENV_SERVE_PORT,
    ENV_SERVE_REPLICA_ID,
    ENV_SERVE_ROLE,
    LABEL_SERVE_INDEX,
    LABEL_SERVE_NAME,
    LABEL_SERVE_ROLE,
    PREFILL_PORT_OFFSET,
    ROLE_PREFILL,
    TPUServe,
    validate_serve_spec,
)
from tf_operator_tpu.fleet import membership as mship
from tf_operator_tpu.fleet.autoscale import Autoscaler, AutoscaleSnapshot
from tf_operator_tpu.fleet.membership import FleetMembership
from tf_operator_tpu.runtime import events as ev
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import Conflict, NotFound
from tf_operator_tpu.scheduler.gang import ANNOTATION_DRAINING_AT
from tf_operator_tpu.utils import logger
from tf_operator_tpu.utils.times import parse_rfc3339

LOG = logger.with_fields(component="fleet-controller")

# Events (the PR 1/2 naming convention: past-tense reason strings).
EVENT_REPLICA_CREATED = "ReplicaCreated"
EVENT_REPLICA_DRAINING = "ReplicaDraining"
EVENT_REPLICA_DELETED = "ReplicaDeleted"
EVENT_REPLICA_DEAD = "ReplicaDead"
EVENT_SCALED = "FleetScaled"
EVENT_ROLLING_UPDATE = "RollingUpdate"
EVENT_REJECTED = "FailedValidation"

COND_FLEET_READY = "FleetReady"


@dataclass
class FleetConfig:
    sync_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    # Consecutive unanswered probes before a replica is DEAD (the
    # process is gone; connection refused is not a health opinion).
    fail_threshold: int = 3
    # How long a JOINING replica may refuse connections before probe
    # failures start counting toward fail_threshold: a real replica
    # spends tens of seconds in gang admission + jax init + warmup
    # before binding its port, and counting those refusals would churn
    # it DEAD→replace→DEAD forever.
    join_grace_s: float = 120.0
    namespace: str | None = None
    # Orphan-child GC runs on its own (longer) period: it is the one
    # sweep that LISTs every TPUJob in the namespace, and doing that at
    # sync_interval_s would reintroduce the per-second list traffic the
    # PR 3 informer caches exist to eliminate. First sync always GCs
    # (a restarted controller may be facing orphans from a TPUServe
    # deleted while it was down).
    gc_interval_s: float = 30.0
    # Seconds a freed replica index (= port portBase+index) is held out
    # of reuse. Deleting a child job only STARTS its teardown — a wedged
    # predecessor can hold the port past the SIGTERM drain — so the
    # successor must not bind the same endpoint immediately; after the
    # quarantine the index is reused, keeping the fleet's index (and
    # port) range bounded by its width, not its replacement history.
    index_quarantine_s: float = 60.0


class TPUServeController:
    """``probe_fn(endpoint) -> /healthz dict`` (raises when unreachable)
    and ``endpoint_fn(serve, index) -> "host:port"`` are injectable:
    production uses real HTTP against ``host:portBase+index``; tests
    point them at in-process FakeReplica servers."""

    def __init__(self, client: Any, *,
                 scheduler: Any = None,
                 recorder: ev.EventRecorder | None = None,
                 config: FleetConfig | None = None,
                 probe_fn: Callable[[str], dict] | None = None,
                 endpoint_fn: Callable[[TPUServe, int], str] | None = None,
                 prefill_endpoint_fn: Callable[[TPUServe, int], str]
                 | None = None,
                 ) -> None:
        self.client = client
        self.scheduler = scheduler
        self.recorder = recorder or ev.EventRecorder(client)
        self.config = config or FleetConfig()
        if probe_fn is None:
            from tf_operator_tpu.fleet.router import http_probe

            probe_fn = lambda ep: http_probe(  # noqa: E731
                ep, self.config.probe_timeout_s
            )
        self._probe_fn = probe_fn
        self._endpoint_fn = endpoint_fn
        self._prefill_endpoint_fn = prefill_endpoint_fn
        self._lock = threading.Lock()
        # Per-fleet state, keyed by "namespace/name".
        self._memberships: dict[str, FleetMembership] = {}
        self._autoscalers: dict[str, Autoscaler] = {}
        self._targets: dict[str, int] = {}
        # Cumulative replicas declared dead per fleet (seeded from the
        # persisted status on first sight): dead rows are deleted and
        # replaced within the SAME sync, so a point-in-time membership
        # count would always report 0.
        self._deaths: dict[str, int] = {}
        # Per-fleet quarantine of freed indices: index -> monotonic time
        # it was freed. Consulted (and expired) by _next_index.
        self._retired: dict[str, dict[int, float]] = {}
        self._last_gc = float("-inf")
        self._thread: threading.Thread | None = None

    # -- per-fleet state ---------------------------------------------------

    def membership_for(self, key: str) -> FleetMembership:
        """The fleet's DECODE replica table (created on first use) —
        what a router for this TPUServe routes /generate from."""
        with self._lock:
            ms = self._memberships.get(key)
            if ms is None:
                ms = self._memberships[key] = FleetMembership(
                    fail_threshold=self.config.fail_threshold,
                    join_grace_s=self.config.join_grace_s,
                    name=key,
                )
            return ms

    def prefill_membership_for(self, key: str) -> FleetMembership:
        """The fleet's PREFILL pool table (disaggregated serving) —
        what a DisaggRouter's first stage routes /prefill from. Keyed
        "{key}#prefill" internally so the two pools can never share a
        pick-set (or a gauge series)."""
        return self.membership_for(f"{key}#prefill")

    def _autoscaler_named(self, key: str, policy: Any) -> Autoscaler:
        with self._lock:
            auto = self._autoscalers.get(key)
            if auto is None or auto.policy != policy:
                # New fleet or edited policy: decisions restart from the
                # spec (cooldown clocks reset — an edited band must not
                # inherit a stale cooldown from the old one).
                auto = Autoscaler(policy)
                self._autoscalers[key] = auto
            return auto

    def _autoscaler_for(self, serve: TPUServe) -> Autoscaler:
        return self._autoscaler_named(serve.key, serve.spec.autoscale)

    def endpoint_of(self, serve: TPUServe, index: int,
                    role: str = "decode") -> str:
        if role == ROLE_PREFILL:
            if self._prefill_endpoint_fn is not None:
                return self._prefill_endpoint_fn(serve, index)
            return (f"{serve.spec.host}:"
                    f"{serve.spec.port_base + PREFILL_PORT_OFFSET + index}")
        if self._endpoint_fn is not None:
            return self._endpoint_fn(serve, index)
        return f"{serve.spec.host}:{serve.spec.port_base + index}"

    # -- decode ------------------------------------------------------------

    def decode_serve(self, obj: dict[str, Any]) -> TPUServe | None:
        try:
            serve = TPUServe.from_dict(obj)
            validate_serve_spec(serve.spec)
            return serve
        except Exception as e:  # noqa: BLE001 — the decode barrier:
            # a bad spec gets an event, never a wedged sync loop.
            self.recorder.warning(obj, EVENT_REJECTED, str(e))
            LOG.warning(f"rejected TPUServe {objects.key_of(obj)}: {e}")
            return None

    # -- child jobs --------------------------------------------------------

    def _children(self, serve: TPUServe) -> tuple[
            dict[int, dict[str, Any]], dict[int, dict[str, Any]]]:
        """(decode, prefill) pools: index -> child TPUJob, split by the
        role label (absent = decode, the pre-disaggregation children).
        From the store — fleet counts are small; a LIST per sync is
        fine at this scale."""
        jobs = self.client.list(
            objects.TPUJOBS, serve.metadata.namespace,
            {LABEL_SERVE_NAME: serve.metadata.name},
        )
        decode: dict[int, dict[str, Any]] = {}
        prefill: dict[int, dict[str, Any]] = {}
        for job in jobs:
            labels = objects.labels_of(job)
            try:
                idx = int(labels[LABEL_SERVE_INDEX])
            except (KeyError, ValueError):
                continue
            if labels.get(LABEL_SERVE_ROLE) == ROLE_PREFILL:
                prefill[idx] = job
            else:
                decode[idx] = job
        return decode, prefill

    def _build_child(self, serve: TPUServe, index: int,
                     role: str = "decode") -> dict[str, Any]:
        prefix = "p" if role == ROLE_PREFILL else "r"
        name = f"{serve.metadata.name}-{prefix}{index}"
        template = copy.deepcopy(serve.spec.template)
        port = self.endpoint_of(serve, index, role).rsplit(":", 1)[1]
        # Single-pool fleets inherit the spec's role pin (a role=prefill
        # TPUServe IS a prefill pool — its -r children run /prefill).
        env_role = role if role == ROLE_PREFILL else (
            serve.spec.role or "decode"
        )
        for c in template.setdefault("spec", {}).setdefault(
            "containers", []
        ):
            if c.get("name") != constants.DEFAULT_CONTAINER_NAME:
                continue
            env = c.setdefault("env", [])
            env.extend([
                {"name": ENV_SERVE_PORT, "value": port},
                {"name": ENV_SERVE_REPLICA_ID, "value": name},
                {"name": ENV_SERVE_MODEL_VERSION,
                 "value": serve.spec.model_version},
                {"name": ENV_SERVE_ROLE, "value": env_role},
            ])
        worker: dict[str, Any] = {"replicas": 1, "template": template}
        if serve.spec.tpu is not None:
            worker["tpu"] = serve.spec.tpu.to_dict()
        spec: dict[str, Any] = {"replicaSpecs": {"Worker": worker}}
        sched = serve.spec.scheduling.to_dict()
        if sched:
            spec["scheduling"] = sched
        labels = {
            LABEL_SERVE_NAME: serve.metadata.name,
            LABEL_SERVE_INDEX: str(index),
        }
        if role == ROLE_PREFILL:
            labels[LABEL_SERVE_ROLE] = ROLE_PREFILL
        return {
            "apiVersion": constants.API_VERSION,
            "kind": constants.KIND,
            "metadata": {
                "name": name,
                "namespace": serve.metadata.namespace,
                "labels": labels,
                "annotations": {
                    ANNOTATION_MODEL_VERSION: serve.spec.model_version,
                },
                "ownerReferences": [{
                    "apiVersion": serve.api_version,
                    "kind": serve.kind,
                    "name": serve.metadata.name,
                    "uid": serve.metadata.uid or "",
                    "controller": True,
                }],
            },
            "spec": spec,
        }

    def _create_replica(self, serve: TPUServe, index: int,
                        role: str = "decode") -> dict[str, Any]:
        """Create the child job and return the dict it was built from
        (callers reuse it for their local view instead of building the
        template a second time)."""
        job = self._build_child(serve, index, role)
        name = objects.name_of(job)
        try:
            self.client.create(objects.TPUJOBS, job)
        except Conflict:
            return job  # a concurrent sync already created it
        ms = (self.prefill_membership_for(serve.key)
              if role == ROLE_PREFILL else self.membership_for(serve.key))
        ms.register(
            name, self.endpoint_of(serve, index, role),
            model_version=serve.spec.model_version,
            role=role,
        )
        self.recorder.normal(
            serve.to_dict(), EVENT_REPLICA_CREATED,
            f"{role} replica {name} created at "
            f"{self.endpoint_of(serve, index, role)}",
        )
        return job

    def _membership_of(self, serve: TPUServe,
                       job: dict[str, Any]) -> FleetMembership:
        """The pool table a child's row lives in, by its role label."""
        if objects.labels_of(job).get(LABEL_SERVE_ROLE) == ROLE_PREFILL:
            return self.prefill_membership_for(serve.key)
        return self.membership_for(serve.key)

    def _begin_drain(self, serve: TPUServe, job: dict[str, Any],
                     reason: str) -> None:
        """Phase 1 of removal: withdraw from routing NOW, exempt the
        gang from preemption, and start the grace clock — the child job
        (and with it the process + its SIGTERM bounded drain) survives
        until ``_finish_drains`` sees the grace expire."""
        name = objects.name_of(job)
        if ANNOTATION_DRAINING_AT in objects.annotations_of(job):
            return  # already draining; the clock is running
        self._membership_of(serve, job).mark_draining(name)
        try:
            self.client.patch_merge(
                objects.TPUJOBS, serve.metadata.namespace, name,
                {"metadata": {"annotations": {
                    ANNOTATION_DRAINING_AT: objects.now_iso(),
                }}},
            )
        except NotFound:
            return
        self.recorder.normal(
            serve.to_dict(), EVENT_REPLICA_DRAINING,
            f"replica {name} draining ({reason}); router deregistered, "
            f"deletion in {serve.spec.scale_down_grace_s:.0f}s",
        )

    def _delete_replica(self, serve: TPUServe, name: str,
                        reason: str, *, index: int | None = None,
                        role: str = "decode") -> None:
        try:
            self.client.delete(
                objects.TPUJOBS, serve.metadata.namespace, name
            )
        except NotFound:
            pass
        # Index quarantines are PER POOL: the pools' port spaces are
        # disjoint, so index 2 freed in one must not block the other's.
        pool_key = (f"{serve.key}#prefill" if role == ROLE_PREFILL
                    else serve.key)
        if index is not None:
            self._retired.setdefault(pool_key, {})[index] = (
                time.monotonic()
            )
        ms = (self.prefill_membership_for(serve.key)
              if role == ROLE_PREFILL else self.membership_for(serve.key))
        ms.deregister(name)
        self.recorder.normal(
            serve.to_dict(), EVENT_REPLICA_DELETED,
            f"replica {name} deleted ({reason})",
        )

    # -- reconcile ---------------------------------------------------------

    def sync_all(self) -> None:
        """One pass over every TPUServe (+ orphan cleanup)."""
        serves = self.client.list(
            objects.TPUSERVES, self.config.namespace, None
        )
        # Orphan GC keys on the objects that EXIST, not the ones that
        # decode: a live fleet whose spec is edited into something
        # invalid must freeze (event + no reconcile), not have its
        # replicas collected as orphans.
        present: set[str] = set()
        for obj in serves:
            present.add(objects.key_of(obj))
            serve = self.decode_serve(obj)
            if serve is None:
                continue
            try:
                self.reconcile_serve(serve)
            except Conflict:
                pass  # stale read; the next sync retries fresh
        self._collect_orphans(present)

    def reconcile_serve(self, serve: TPUServe) -> None:
        key = serve.key
        ms = self.membership_for(key)
        children, prefill_children = self._children(serve)
        version = serve.spec.model_version

        # 1. Register every child (idempotent) and sweep probes. A
        # draining annotation re-marks the row each sync, so a restarted
        # controller recovers drain state from the store, not memory.
        for idx, job in sorted(children.items()):
            name = objects.name_of(job)
            rep = ms.register(
                name, self.endpoint_of(serve, idx),
                model_version=objects.annotations_of(job).get(
                    ANNOTATION_MODEL_VERSION, ""
                ),
            )
            if (ANNOTATION_DRAINING_AT in objects.annotations_of(job)
                    and rep.state != mship.DEAD):
                ms.mark_draining(name)
        child_names = {objects.name_of(j) for j in children.values()}
        for rid in [r.id for r in ms.all()]:
            if rid not in child_names:
                ms.deregister(rid)  # child gone outside our delete path
        ms.probe(self._probe_fn)

        # 2. Cordon → router eviction (and back): the health machinery
        # owns the gang migration; membership only mirrors it so the
        # router stops sending traffic into a cell being drained.
        if self.scheduler is not None:
            cordoned = set(self.scheduler.gangs_on_cordoned_cells())
            for idx, job in children.items():
                name = objects.name_of(job)
                rep = ms.get(name)
                if rep is None:
                    continue
                child_key = f"{serve.metadata.namespace}/{name}"
                if child_key in cordoned:
                    if rep.state in (mship.READY, mship.JOINING):
                        ms.mark_cordoned(name)
                elif rep.state == mship.CORDONED:
                    ms.uncordon(name)

        # 3. Autoscale target (or the spec's replica count, clamped).
        counts = ms.counts()
        auto = self._autoscaler_for(serve)
        # Drained unconditionally so a later policy enable starts from
        # a fresh window, not months of accumulated rejections.
        unrouted = ms.take_unrouted()
        if serve.spec.autoscale.enabled:
            current = self._targets.get(key)
            if current is None:
                # First sight of this fleet (or a restarted/failed-over
                # controller): resume the persisted status.target rather
                # than snapping back to spec.replicas — snapping would
                # drain autoscaled-up replicas in one sync, bypassing
                # the two-observation scale-down hysteresis.
                # last_reconcile_time distinguishes "status was really
                # written" from the TPUServeStatus default: a fleet
                # legitimately scaled to target 0 (minReplicas 0) must
                # resume at 0, not snap back to spec.replicas and
                # recreate everything the autoscaler drained.
                persisted = serve.status.target
                reconciled = bool(serve.status.last_reconcile_time)
                current = auto.clamp(
                    persisted if persisted > 0 or reconciled
                    else serve.spec.replicas
                )
            target = auto.decide(
                AutoscaleSnapshot(
                    ready=counts[mship.READY],
                    queue_depth=ms.aggregate_queue_depth(),
                    ttft_p99_s=ms.fleet_ttft_p99(),
                    unrouted=unrouted,
                    # Decode-pool signals (disaggregated fleets): the
                    # policy's occupancy/ITL thresholds read these;
                    # both default off, so plain fleets are unchanged.
                    occupancy=ms.mean_occupancy(),
                    itl_p99_s=ms.fleet_itl_p99(),
                ),
                current,
            )
            if target != current:
                self.recorder.normal(
                    serve.to_dict(), EVENT_SCALED,
                    f"autoscale {current} -> {target}: "
                    f"{auto.last_reason}",
                )
        else:
            target = serve.spec.replicas
        self._targets[key] = target

        draining_names = self._draining_names(children)
        # 4. Replace dead replicas first: they serve nothing, so no
        # drain phase — delete now, recreate at a free index below.
        # Draining children are NOT deaths even when their process is
        # already gone (an early drain exit is the drain SUCCEEDING):
        # _finish_drains deletes those without waiting out the grace.
        for idx, job in sorted(children.items()):
            name = objects.name_of(job)
            if name in draining_names:
                continue
            rep = ms.get(name)
            if rep is not None and rep.state == mship.DEAD:
                self.recorder.warning(
                    serve.to_dict(), EVENT_REPLICA_DEAD,
                    f"replica {name} dead "
                    f"({rep.consecutive_failures} failed probe(s), "
                    f"{rep.watchdog_restarts} watchdog restart(s)); "
                    "replacing",
                )
                self._deaths[key] = self._deaths.get(
                    key, serve.status.dead
                ) + 1
                self._delete_replica(serve, name, "dead", index=idx)
                children.pop(idx)

        # 5. Rolling update, one replica at a time. Invariant: drain a
        # stale replica ONLY while surge surplus exists (live > target
        # AND a new-version replica probes READY), and surge ONLY while
        # there is no surplus — so ready-capable capacity never dips
        # below target, and each drained stale replica's deletion
        # re-creates the surge for the next one.
        live = {
            i: j for i, j in children.items()
            if objects.name_of(j) not in draining_names
        }
        stale = sorted(
            i for i, j in live.items()
            if objects.annotations_of(j).get(ANNOTATION_MODEL_VERSION, "")
            != version
        )
        if stale:
            fresh_ready = [
                i for i in live
                if i not in stale
                and (r := ms.get(objects.name_of(live[i]))) is not None
                and r.state == mship.READY
            ]
            if len(live) <= target:
                idx = self._next_index(serve, children)
                children[idx] = live[idx] = self._create_replica(
                    serve, idx
                )
                self.recorder.normal(
                    serve.to_dict(), EVENT_ROLLING_UPDATE,
                    f"surging replica r{idx} at version {version!r} "
                    f"({len(stale)} stale replica(s) to replace)",
                )
            elif fresh_ready:
                # The surge replica serves: cut one old one loose. The
                # router deregistered it the moment the drain began, so
                # the cutover drops nothing.
                victim = live[stale[0]]
                self._begin_drain(
                    serve, victim, f"rolling update to {version!r}"
                )
                draining_names.add(objects.name_of(victim))
            elif len(stale) == len(live):
                # Target fell below the live count mid-roll (spec edit
                # or autoscaler down-step) and no new-version replica
                # exists to wait on: the surplus is excess, not surge.
                # Drain one stale replica per sync — live stays >=
                # target throughout, and once live == target the surge
                # branch above takes over the roll.
                victim = live[stale[0]]
                self._begin_drain(
                    serve, victim,
                    f"rolling update to {version!r} (shrinking stale "
                    "surplus above target)",
                )
                draining_names.add(objects.name_of(victim))

        # 6. Scale to target (draining replicas are neither capacity
        # nor candidates — they are already on their way out). Plain
        # scale-down holds while a roll is in flight: the surge surplus
        # above is intentional, not excess.
        active = {
            i: j for i, j in children.items()
            if objects.name_of(j) not in draining_names
        }
        while len(active) < target:
            idx = self._next_index(serve, children)
            children[idx] = active[idx] = self._create_replica(
                serve, idx
            )
        if len(active) > target and not stale:
            # Highest index first: deterministic, and the longest-lived
            # replicas (warmest caches) survive.
            for idx in sorted(active, reverse=True)[
                : len(active) - target
            ]:
                self._begin_drain(serve, active[idx], "scale down")
                draining_names.add(objects.name_of(active[idx]))
                active.pop(idx)

        # 7. Finish expired drains: grace over → delete the child; the
        # executor's SIGTERM delivery starts the process's own bounded
        # drain (admitted requests finish inside --drain-timeout).
        self._finish_drains(serve, children)

        # 8. The prefill pool (disaggregated fleets; no-op otherwise).
        prefill_target = self._reconcile_prefill_pool(
            serve, prefill_children
        )

        # 9. Status roll-up.
        self._write_status(serve, children, target, prefill_children,
                           prefill_target)

    def _draining_names(self, children: dict[int, dict]) -> set[str]:
        return {
            objects.name_of(j) for j in children.values()
            if ANNOTATION_DRAINING_AT in objects.annotations_of(j)
        }

    def _next_index(self, serve: TPUServe, children: dict[int, dict],
                    role: str = "decode") -> int:
        """Lowest index neither held by an existing child (live OR
        draining — its process still owns the port) nor inside the
        reuse quarantine (per POOL: the pools' port spaces are
        disjoint). Bounded: a fleet's indices never exceed its peak
        width plus the handful quarantined at any moment, so
        ``portBase + index`` stays inside the validated port range no
        matter how many replacements a long-lived fleet goes through."""
        now = time.monotonic()
        pool_key = (f"{serve.key}#prefill" if role == ROLE_PREFILL
                    else serve.key)
        retired = self._retired.get(pool_key, {})
        for i, freed_at in list(retired.items()):
            if now - freed_at >= self.config.index_quarantine_s:
                retired.pop(i)
        idx = 0
        while idx in children or idx in retired:
            idx += 1
        return idx

    def _finish_drains(self, serve: TPUServe,
                       children: dict[int, dict],
                       role: str = "decode") -> None:
        for idx, job in sorted(children.items()):
            stamp = objects.annotations_of(job).get(ANNOTATION_DRAINING_AT)
            if not stamp:
                continue
            name = objects.name_of(job)
            started = parse_rfc3339(stamp)
            rep = self._membership_of(serve, job).get(name)
            drained = rep is not None and rep.state == mship.DEAD
            if drained or started is None or (
                time.time() - started >= serve.spec.scale_down_grace_s
            ):
                self._delete_replica(
                    serve, name, "drain complete", index=idx, role=role
                )
                children.pop(idx)

    def _reconcile_prefill_pool(self, serve: TPUServe,
                                children: dict[int, dict]) -> int:
        """The disaggregated fleet's SECOND pool, reconciled with the
        same verbs as the decode pool but simpler policies: prefill
        replicas are STATELESS (no admitted decodes to protect), so
        there is no surge-then-drain roll — a stale-version replica
        drains (one per sync) and the top-up loop recreates it at the
        new version; dead ones are replaced at quarantined-reuse
        indices; the pool scales on ITS OWN signal — prefill queue
        depth per ready replica (``spec.prefillAutoscale``) — because a
        prefill pool has no occupancy or ITL to read. Returns the
        pool's target."""
        key = serve.key
        want = serve.spec.prefill_replicas
        pol = serve.spec.prefill_autoscale
        if not (want or pol.enabled or children):
            return 0
        pms = self.prefill_membership_for(key)

        # Register + probe (drain state recovered from the store).
        for idx, job in sorted(children.items()):
            name = objects.name_of(job)
            rep = pms.register(
                name, self.endpoint_of(serve, idx, ROLE_PREFILL),
                model_version=objects.annotations_of(job).get(
                    ANNOTATION_MODEL_VERSION, ""
                ),
                role=ROLE_PREFILL,
            )
            if (ANNOTATION_DRAINING_AT in objects.annotations_of(job)
                    and rep.state != mship.DEAD):
                pms.mark_draining(name)
        child_names = {objects.name_of(j) for j in children.values()}
        for rid in [r.id for r in pms.all()]:
            if rid not in child_names:
                pms.deregister(rid)
        pms.probe(self._probe_fn)

        # Autoscale on prefill queue depth (or pin to the spec count).
        counts = pms.counts()
        auto_key = f"{key}#prefill"
        # Drained unconditionally, exactly like the decode pool's: the
        # stage-1 router notes no_replica answers onto THIS table, and
        # they are the only demand signal a prefill pool scaled to
        # zero can emit (nothing exists to queue on).
        unrouted = pms.take_unrouted()
        if pol.enabled:
            auto = self._autoscaler_named(auto_key, pol)
            current = self._targets.get(auto_key)
            if current is None:
                persisted = serve.status.prefill_target
                reconciled = bool(serve.status.last_reconcile_time)
                current = auto.clamp(
                    persisted if persisted > 0 or reconciled else want
                )
            target = auto.decide(
                AutoscaleSnapshot(
                    ready=counts[mship.READY],
                    queue_depth=pms.aggregate_queue_depth(),
                    unrouted=unrouted,
                ),
                current,
            )
            if target != current:
                self.recorder.normal(
                    serve.to_dict(), EVENT_SCALED,
                    f"prefill autoscale {current} -> {target}: "
                    f"{auto.last_reason}",
                )
        else:
            target = want
        self._targets[auto_key] = target

        # Replace the dead (no drain phase — they serve nothing).
        draining_names = self._draining_names(children)
        for idx, job in sorted(children.items()):
            name = objects.name_of(job)
            if name in draining_names:
                continue
            rep = pms.get(name)
            if rep is not None and rep.state == mship.DEAD:
                self.recorder.warning(
                    serve.to_dict(), EVENT_REPLICA_DEAD,
                    f"prefill replica {name} dead "
                    f"({rep.consecutive_failures} failed probe(s)); "
                    "replacing",
                )
                self._deaths[key] = self._deaths.get(
                    key, serve.status.dead
                ) + 1
                self._delete_replica(serve, name, "dead", index=idx,
                                     role=ROLE_PREFILL)
                children.pop(idx)

        # Version roll, stateless style: drain ONE stale per sync; the
        # top-up below recreates at the new version in the same pass.
        active = {
            i: j for i, j in children.items()
            if objects.name_of(j) not in draining_names
        }
        stale = sorted(
            i for i, j in active.items()
            if objects.annotations_of(j).get(ANNOTATION_MODEL_VERSION, "")
            != serve.spec.model_version
        )
        if stale:
            victim = active.pop(stale[0])
            self._begin_drain(
                serve, victim,
                f"prefill roll to {serve.spec.model_version!r}",
            )
            draining_names.add(objects.name_of(victim))

        # Scale to target.
        while len(active) < target:
            idx = self._next_index(serve, children, ROLE_PREFILL)
            children[idx] = active[idx] = self._create_replica(
                serve, idx, ROLE_PREFILL
            )
        if len(active) > target:
            for idx in sorted(active, reverse=True)[
                : len(active) - target
            ]:
                self._begin_drain(serve, active[idx], "scale down")
                draining_names.add(objects.name_of(active[idx]))
                active.pop(idx)

        self._finish_drains(serve, children, ROLE_PREFILL)
        return target

    def _collect_orphans(self, seen: set[str]) -> None:
        """Children whose TPUServe is gone: delete them and drop the
        per-fleet state (controller-side GC — ownerReferences also cover
        backends with a real GC, but the in-memory store has none for
        TPUServe parents).

        The namespace-wide TPUJob LIST is throttled to gc_interval_s;
        the in-memory per-fleet state cleanup is free and runs every
        sync."""
        now = time.monotonic()
        if now - self._last_gc >= self.config.gc_interval_s:
            self._last_gc = now
            jobs = self.client.list(
                objects.TPUJOBS, self.config.namespace, None
            )
            for job in jobs:
                labels = objects.labels_of(job)
                serve_name = labels.get(LABEL_SERVE_NAME)
                if not serve_name:
                    continue
                key = f"{objects.namespace_of(job)}/{serve_name}"
                if key in seen:
                    continue
                try:
                    self.client.delete(
                        objects.TPUJOBS, objects.namespace_of(job),
                        objects.name_of(job),
                    )
                except NotFound:
                    pass
                LOG.info(
                    f"deleted orphan replica {objects.key_of(job)} "
                    f"(TPUServe {key} is gone)"
                )
        with self._lock:
            for key in list(self._memberships):
                # Pool tables key "{fleet}" / "{fleet}#prefill": both
                # live exactly as long as their TPUServe.
                if key.split("#", 1)[0] not in seen:
                    self._memberships.pop(key).close()
                    self._autoscalers.pop(key, None)
                    self._targets.pop(key, None)
                    self._deaths.pop(key, None)
                    self._retired.pop(key, None)

    # -- status ------------------------------------------------------------

    def _write_status(self, serve: TPUServe, children: dict[int, dict],
                      target: int,
                      prefill_children: dict[int, dict] | None = None,
                      prefill_target: int = 0) -> None:
        ms = self.membership_for(serve.key)
        counts = ms.counts()
        status = serve.status
        before = status.to_dict()
        status.replicas = len(children)
        status.ready = counts[mship.READY]
        status.draining = counts[mship.DRAINING]
        # Cumulative: a dead replica is deleted + deregistered in the
        # same sync that sees it, so counts[DEAD] here is always 0.
        status.dead = self._deaths.get(serve.key, status.dead)
        status.target = target
        status.prefill_replicas = len(prefill_children or {})
        status.prefill_target = prefill_target
        status.prefill_ready = (
            self.prefill_membership_for(serve.key).counts()[mship.READY]
            if prefill_children or prefill_target else 0
        )
        versions = {
            r.model_version for r in ms.all() if r.state == mship.READY
        }
        status.model_version = (
            versions.pop() if len(versions) == 1 else ""
        )
        ready_now = (
            (target == 0 or status.ready >= target)
            and status.prefill_ready >= prefill_target
        )
        msg = (
            f"{status.ready}/{target} replicas ready"
            + (f", {status.draining} draining" if status.draining else "")
        )
        if prefill_target or status.prefill_replicas:
            msg += (f"; prefill {status.prefill_ready}/"
                    f"{prefill_target} ready")
        self._set_condition(
            serve, COND_FLEET_READY,
            "True" if ready_now else "False",
            reason="AllReplicasReady" if ready_now else "FleetPending",
            message=msg,
        )
        after = status.to_dict()
        if after == before:
            return
        status.last_reconcile_time = objects.now_iso()
        for attempt in range(3):
            try:
                self.client.update_status(
                    objects.TPUSERVES, serve.to_dict()
                )
                return
            except Conflict:
                if attempt == 2:
                    raise
                try:
                    fresh = self.client.get(
                        objects.TPUSERVES, serve.metadata.namespace,
                        serve.metadata.name,
                    )
                except NotFound:
                    return
                serve.metadata.resource_version = str(
                    objects.meta(fresh).get("resourceVersion", "")
                )
            except NotFound:
                return

    def _set_condition(self, serve: TPUServe, ctype: str, value: str,
                       *, reason: str, message: str) -> None:
        from tf_operator_tpu.api.types import JobCondition

        for cond in serve.status.conditions:
            if cond.type == ctype:
                if cond.status != value or cond.message != message:
                    cond.status = value
                    cond.reason = reason
                    cond.message = message
                    cond.last_transition_time = objects.now_iso()
                return
        serve.status.conditions.append(JobCondition(
            type=ctype, status=value, reason=reason, message=message,
            last_transition_time=objects.now_iso(),
        ))

    # -- snapshots / run ---------------------------------------------------

    def debug_snapshot(self) -> dict[str, Any]:
        """The /debug/fleet controller section: per-fleet membership +
        target + autoscaler state; disaggregated fleets carry their
        prefill pool under a ``prefill`` sub-entry of the SAME fleet
        key (tpuctl serve renders both pools)."""
        # Membership/autoscaler references are captured under the lock
        # (a concurrent fleet deletion pops these dicts mid-iteration);
        # the snapshot() calls run outside it — they take their own
        # locks and must not nest under ours.
        with self._lock:
            rows = [
                (key, self._targets.get(key, 0), ms,
                 self._autoscalers.get(key))
                for key, ms in sorted(self._memberships.items())
            ]
        fleets: dict[str, dict] = {}
        for key, target, ms, auto in rows:
            base, _, pool = key.partition("#")
            entry = {
                "target": target,
                "membership": ms.snapshot(),
                "autoscale": (
                    auto.snapshot() if auto is not None else None
                ),
            }
            if pool == "prefill":
                fleets.setdefault(base, {})["prefill"] = entry
            else:
                # The fleet-wide prefix directory (distinct advertised
                # digests / advertising replicas) rides the decode pool
                # entry: prefix routing reads decode advertisements only.
                entry["prefixes"] = ms.prefix_directory()
                fleets.setdefault(base, {}).update(entry)
        return {"fleets": fleets}

    def start(self, stop: threading.Event,
              interval: float | None = None) -> None:
        """Background reconcile loop (the operator runs this only while
        leading — a standby must not create or drain replicas)."""
        period = interval or self.config.sync_interval_s

        def loop() -> None:
            while not stop.wait(period):
                try:
                    self.sync_all()
                except Exception:  # noqa: BLE001 — one bad pass must
                    # not kill the loop; the next interval retries.
                    LOG.exception("fleet sync failed")

        self._thread = threading.Thread(
            target=loop, daemon=True, name="fleet-controller"
        )
        self._thread.start()
