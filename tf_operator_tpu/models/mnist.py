"""MNIST CNN — the framework's smoke-test model.

Parity role: the reference's dist_mnist.py sample (examples/v1alpha2/
dist-mnist/) — the minimal end-to-end workload (BASELINE.json configs[0..2]).
Small enough to train to >95% accuracy in seconds on one chip or a CPU mesh.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x.astype(jnp.float32))
        return x
